"""Aggregations: parse JSON -> agg specs, build device descs, reduce partials.

Reference analog: search/aggregations/ — AggregatorParsers/AggregatorFactories
build an aggregator tree wrapped as a Lucene Collector
(AggregationPhase.java:95); every InternalAggregation implements
reduce(ReduceContext) for the coordinating-node merge
(InternalAggregation.java:149).

Here: the device part is a desc tree interpreted by
search/executor.py:eval_aggs (masked scatter-add kernels); the partial
bucket arrays coming back per segment/shard are reduced by plain
numpy addition/min/max keyed on shard-global ordinals or histogram
bucket ids — the InternalAggregation.reduce analog. Keyword buckets
merge across shards by TERM STRING (shards own different ordinal
spaces), exactly like InternalTerms.reduce does.
"""

from __future__ import annotations

import datetime as _dt
from dataclasses import dataclass, field as dc_field

import numpy as np

from ..index.mapping import parse_date_millis, format_date_millis, DATE
from ..index.segment import Segment, next_pow2
from ..utils.errors import SearchParseError

METRIC_KINDS = ("avg", "sum", "min", "max", "stats", "extended_stats", "value_count")
# derived bucket aggs run as auxiliary filtered sub-requests over the same
# readers (ref: bucket/filter/FilterAggregator.java, filters/, range/,
# missing/, global/ — their collectors wrap a per-bucket doc filter; here
# each bucket IS a filtered query, so nested sub-aggregations of any kind
# come along for free through the batched executor)
DERIVED_KINDS = ("filter", "filters", "range", "date_range", "missing",
                 "global", "top_hits", "nested", "reverse_nested",
                 "children", "significant_terms")
_PCTL_BINS = 2048  # device histogram resolution for percentiles — a
                   # scatter over 2048 lanes costs the same VPU pass as
                   # 256 and cuts bin quantization error 8x; combined
                   # with centroid interpolation (percentile_values)
                   # this tracks t-digest accuracy on unimodal data
DEFAULT_PERCENTS = (1.0, 5.0, 25.0, 50.0, 75.0, 95.0, 99.0)
_FIXED_UNITS_S = {
    "second": 1, "1s": 1, "minute": 60, "1m": 60, "hour": 3600, "1h": 3600,
    "day": 86400, "1d": 86400, "week": 604800, "1w": 604800,
}
_CALENDAR_UNITS = ("month", "1M", "quarter", "1q", "year", "1y")
_SUFFIX_S = {"s": 1, "m": 60, "h": 3600, "d": 86400, "w": 604800}


@dataclass
class AggSpec:
    name: str
    kind: str                       # terms | date_histogram | histogram | metric kinds | cardinality
    field: str
    size: int = 10                  # terms bucket count returned
    interval: str | float | None = None
    min_doc_count: int = 1
    order: tuple[str, str] = ("_count", "desc")
    sub_metrics: list["AggSpec"] = dc_field(default_factory=list)
    # derived kinds: [(bucket_key, filter_query_dict|None, extra_json)]
    buckets: list = dc_field(default_factory=list)
    mode: str = "and"               # and (filter query) | ignore_query (global)
    sub_raw: dict = dc_field(default_factory=dict)   # nested aggs, re-parsed
    percents: tuple = DEFAULT_PERCENTS
    top_hits_size: int = 3
    top_hits_source: object = True
    precision: int = 5              # geohash_grid precision (chars)
    precision_threshold: int = 3000  # cardinality: exact below, HLL above
    fmt: str | None = None          # histogram key format pattern
    # terms-level significant_terms sub-aggs: {name: raw conf}; computed
    # host-side per bucket (ref: SignificantTermsAggregatorFactory
    # nested under GlobalOrdinalsStringTermsAggregator)
    sig_subs: dict = dc_field(default_factory=dict)


def parse_aggs(body: dict | None) -> list[AggSpec]:
    """Parse the `aggs`/`aggregations` section of a search request."""
    if not body:
        return []
    specs = []
    for name, spec in body.items():
        if not isinstance(spec, dict):
            raise SearchParseError(f"aggregation [{name}] must be an object")
        sub = spec.get("aggs") or spec.get("aggregations") or {}
        kinds = [k for k in spec if k not in ("aggs", "aggregations", "meta")]
        if len(kinds) != 1:
            raise SearchParseError(f"aggregation [{name}] must define one type")
        kind = kinds[0]
        conf = spec[kind]
        if kind in DERIVED_KINDS or kind in ("percentiles",
                                             "percentile_ranks",
                                             "significant_terms"):
            specs.append(_parse_special(name, kind, conf, sub))
            continue
        if kind not in ("terms", "date_histogram", "histogram", "cardinality",
                        "geo_bounds", "geo_centroid", "geohash_grid",
                        "scripted_metric", *METRIC_KINDS):
            raise SearchParseError(f"unknown aggregation type [{kind}]")
        order = ("_count", "desc")
        if kind == "terms" and isinstance(conf.get("order"), dict):
            ok, ov = next(iter(conf["order"].items()))
            order = (ok, str(ov).lower())
        raw_size = int(conf.get("size", 10) or 0)
        agg = AggSpec(
            name=name, kind=kind, field=conf.get("field"),
            # size 0 = "all buckets" (ES 2.x semantics)
            size=raw_size if raw_size > 0 else (1 << 31),
            interval=conf.get("interval"),
            min_doc_count=int(conf.get("min_doc_count", 1)),
            order=order,
        )
        if kind == "scripted_metric":
            # restricted scripted_metric (ref: metrics/scripted/
            # ScriptedMetricAggregator.java): map_script is a device
            # expression producing one number per doc; combine/reduce =
            # sum (per-shard and cross-shard). The Groovy free-form _agg
            # state machine has no tensor analog.
            ms = conf.get("map_script")
            if ms is None:
                raise SearchParseError(
                    f"[scripted_metric] agg [{name}] requires [map_script]")
            agg.field = _script_field_tag(ms, conf.get("params"))
        elif agg.field is None and conf.get("script") is not None \
                and kind in METRIC_KINDS:
            # metric aggs over a script instead of a field (ref:
            # ValuesSourceParser script mode)
            agg.field = _script_field_tag(conf["script"],
                                          conf.get("params"))
        if agg.field is None:
            raise SearchParseError(f"aggregation [{name}] requires [field]")
        if kind == "geohash_grid":
            agg.precision = int(conf.get("precision", 5))
            if not 1 <= agg.precision <= 12:
                raise SearchParseError(
                    f"[geohash_grid] precision must be 1..12, got "
                    f"{agg.precision}")
            agg.size = int(conf.get("size", 10000) or 10000)
        if kind == "terms" and sub:
            # significant_terms under terms runs as per-bucket aux
            # requests after the main program; strip before the
            # metric-only sub parse
            sub = dict(sub)
            for sname in list(sub):
                sk = [k for k in sub[sname]
                      if k not in ("aggs", "aggregations", "meta")]
                if sk == ["significant_terms"]:
                    agg.sig_subs[sname] = sub.pop(sname)[
                        "significant_terms"]
        if kind == "histogram" and conf.get("format"):
            agg.fmt = str(conf["format"])
        if kind == "cardinality" and conf.get("precision_threshold") \
                is not None:
            agg.precision_threshold = int(conf["precision_threshold"])
        for sname, sspec in parse_sub_metrics(name, sub).items():
            agg.sub_metrics.append(sspec)
            _ = sname
        specs.append(agg)
    return specs


def _script_field_tag(script, params: dict | None) -> str:
    """Encode a script + its (numeric) params as a pseudo field name so
    it participates in the static jit cache key like a real column."""
    from ..script import parse_script_spec, compile_script
    from ..script.service import numeric_param
    src, sparams = parse_script_spec(script if isinstance(script, dict)
                                     else {"script": script})
    if params:
        sparams = {**sparams, **params}
    compile_script(src)  # surface parse errors at request time
    ptag = ",".join(f"{k}={numeric_param(k, v)}"
                    for k, v in sorted(sparams.items()))
    return f"_script\x00{src}\x00{ptag}"


def _range_key(frm, to) -> str:
    """ES range bucket keys: "a-b" with * for open ends."""
    return f"{frm if frm is not None else '*'}-{to if to is not None else '*'}"


def _parse_special(name: str, kind: str, conf, sub: dict) -> AggSpec:
    """Derived bucket aggs + percentiles (see DERIVED_KINDS)."""
    spec = AggSpec(name=name, kind=kind, field=None, sub_raw=dict(sub))
    if kind == "filter":
        spec.buckets = [(name, conf if conf else {"match_all": {}}, {})]
    elif kind == "filters":
        raw = conf.get("filters")
        if isinstance(raw, dict):
            spec.buckets = [(k, q, {}) for k, q in raw.items()]
        elif isinstance(raw, list):
            spec.buckets = [(f"_{i}", q, {}) for i, q in enumerate(raw)]
        else:
            raise SearchParseError(f"[filters] agg [{name}] requires [filters]")
    elif kind in ("range", "date_range"):
        field = conf.get("field")
        if field is None:
            raise SearchParseError(f"[{kind}] agg [{name}] requires [field]")
        spec.field = field
        for r in conf.get("ranges") or []:
            frm, to = r.get("from"), r.get("to")
            rq: dict = {}
            if frm is not None:
                rq["gte"] = frm
            if to is not None:
                rq["lt"] = to
            key = r.get("key") or _range_key(frm, to)
            spec.buckets.append((key, {"range": {field: rq}} if rq
                                 else {"exists": {"field": field}},
                                 {"from": frm, "to": to}))
        if not spec.buckets:
            raise SearchParseError(f"[{kind}] agg [{name}] requires [ranges]")
    elif kind == "missing":
        field = conf.get("field")
        if field is None:
            raise SearchParseError(f"[missing] agg [{name}] requires [field]")
        spec.field = field
        spec.buckets = [(name, {"bool": {"must_not": [
            {"exists": {"field": field}}]}}, {})]
    elif kind == "global":
        spec.buckets = [(name, None, {})]
        spec.mode = "ignore_query"
    elif kind == "nested":
        # ref: bucket/nested/NestedAggregator.java — scope shifts to the
        # hidden block-join child rows of `path`
        path = (conf or {}).get("path")
        if not path:
            raise SearchParseError(f"[nested] agg [{name}] requires [path]")
        spec.mode = f"nested:{path}"
        spec.buckets = [(name, None, {})]
    elif kind == "reverse_nested":
        # ref: bucket/nested/ReverseNestedAggregator.java — scope shifts
        # back to the parent documents of the enclosing nested scope
        spec.mode = "reverse_nested"
        spec.buckets = [(name, None, {})]
    elif kind == "children":
        # ref: bucket/children/ParentToChildrenAggregator.java
        ctype = (conf or {}).get("type")
        if not ctype:
            raise SearchParseError(f"[children] agg [{name}] requires [type]")
        spec.mode = f"children:{ctype}"
        spec.buckets = [(name, None, {})]
    elif kind == "top_hits":
        spec.buckets = [(name, {"match_all": {}}, {})]
        spec.top_hits_size = int(conf.get("size", 3))
        spec.top_hits_source = conf.get("_source", True)
    elif kind == "percentiles":
        field = conf.get("field")
        if field is None:
            raise SearchParseError(
                f"[percentiles] agg [{name}] requires [field]")
        spec.field = field
        if conf.get("percents"):
            spec.percents = tuple(float(p) for p in conf["percents"])
    elif kind == "percentile_ranks":
        # ref: metrics/percentiles/PercentileRanksParser.java — same
        # device histogram as percentiles, inverse interpolation
        field = conf.get("field")
        if field is None:
            raise SearchParseError(
                f"[percentile_ranks] agg [{name}] requires [field]")
        spec.field = field
        values = conf.get("values")
        if not values:
            raise SearchParseError(
                f"[percentile_ranks] agg [{name}] requires [values]")
        spec.percents = tuple(float(v) for v in values)
    elif kind == "significant_terms":
        # ref: bucket/significant/SignificantTermsAggregatorFactory.java
        # + heuristic JLHScore.java — foreground (query) vs background
        # (index) term frequency comparison via two terms aux requests
        field = conf.get("field")
        if field is None:
            raise SearchParseError(
                f"[significant_terms] agg [{name}] requires [field]")
        if sub:
            raise SearchParseError(
                f"[significant_terms] agg [{name}]: sub-aggregations are "
                f"not supported yet")
        spec.field = field
        spec.size = int(conf.get("size", 10) or 10)
        spec.min_doc_count = int(conf.get("min_doc_count", 3))
        spec.buckets = [("fg", None, {}), ("bg", None, {})]
        spec.sub_raw = {"__sig_terms": {
            "terms": {"field": field, "size": 10_000}}}
    return spec


def parse_sub_metrics(parent: str, sub: dict) -> dict[str, AggSpec]:
    out = {}
    for sname, sspec in sub.items():
        kinds = [k for k in sspec if k not in ("aggs", "aggregations", "meta")]
        if len(kinds) != 1:
            raise SearchParseError(f"sub-aggregation [{sname}] must define one type")
        kind = kinds[0]
        if kind not in METRIC_KINDS:
            raise SearchParseError(
                f"sub-aggregation [{sname}] of [{parent}]: only metric "
                f"sub-aggregations are supported at this level, got [{kind}]")
        out[sname] = AggSpec(name=sname, kind=kind, field=sspec[kind].get("field"))
    return out


def parse_interval_seconds(interval) -> int | None:
    """Fixed interval in seconds, or None if it's a calendar interval."""
    if interval is None:
        raise SearchParseError("date_histogram requires [interval]")
    if isinstance(interval, (int, float)):
        return max(int(interval) // 1000, 1)  # bare numbers are millis
    s = str(interval)
    if s in _CALENDAR_UNITS:
        return None
    if s in _FIXED_UNITS_S:
        return _FIXED_UNITS_S[s]
    unit = s[-1]
    if unit in _SUFFIX_S:
        try:
            return max(int(float(s[:-1]) * _SUFFIX_S[unit]), 1)
        except ValueError:
            pass
    if s.endswith("ms"):
        try:
            return max(int(float(s[:-2]) / 1000.0), 1)
        except ValueError:
            pass
    raise SearchParseError(f"failed to parse date_histogram interval [{interval}]")


def calendar_edges(min_s: int, max_s: int, unit: str) -> np.ndarray:
    """Bucket edges (epoch seconds) for calendar intervals month/quarter/year."""
    months = {"month": 1, "1M": 1, "quarter": 3, "1q": 3, "year": 12, "1y": 12}[unit]
    start = _dt.datetime.fromtimestamp(min_s, _dt.timezone.utc)
    start = start.replace(day=1, hour=0, minute=0, second=0, microsecond=0)
    if months == 3:
        start = start.replace(month=((start.month - 1) // 3) * 3 + 1)
    elif months == 12:
        start = start.replace(month=1)
    edges = []
    cur = start
    while True:
        edges.append(int(cur.timestamp()))
        if cur.timestamp() > max_s:
            break
        month0 = cur.month - 1 + months
        cur = cur.replace(year=cur.year + month0 // 12, month=month0 % 12 + 1)
    return np.asarray(edges, dtype=np.int64)


# ---------------------------------------------------------------------------
# Device desc construction (shard-level statics shared by all its segments)
# ---------------------------------------------------------------------------


class ShardAggContext:
    """Builds the static agg desc + per-segment params for one shard view.

    Needs shard-global keyword ordinal registries and the data extent of
    histogram fields so all segments produce aligned partial arrays.
    """

    def __init__(self, segments: list[Segment],
                 global_ords: dict[str, tuple[list[str], list[np.ndarray]]],
                 allow_device_topk: bool = True,
                 extent_override: dict | None = None):
        self.segments = segments
        self.global_ords = global_ords  # field -> (terms, seg2global per segment)
        # mesh-global extents (field -> (lo, hi) | None): multi-host
        # packs inject these so histogram origins/bucket counts — which
        # are static program shape — derive from the same numbers on
        # every host, not from each host's local segments
        self.extent_override = extent_override or {}
        # device-side shard_size selection for high-cardinality terms:
        # downloading [B, n_global] counts dominates when n_global is
        # large, so the program ships only each segment's top buckets.
        # The mesh path disables this (its in-program shard reduce psums
        # aligned count arrays).
        self.allow_device_topk = allow_device_topk
        self.edges: dict[str, np.ndarray] = {}       # agg name -> bucket edges
        self.origins: dict[str, tuple[int | float, int | float, int]] = {}
        # date_histogram column unit: DATE columns hold epoch seconds
        # (int32-exact); other numeric columns are interpreted as epoch
        # millis like ES does for long fields. Partial keys are always
        # normalized to millis so shards with different mappings merge.
        self.date_unit: dict[str, int] = {}          # agg name -> 1000 (s) | 1 (ms)
        # cardinality aggs that switched to the HLL++ sketch (ref:
        # HyperLogLogPlusPlus precision_threshold switchover)
        self.hll_names: set[str] = set()

    def _is_date_column(self, field: str) -> bool:
        for seg in self.segments:
            nc = seg.numerics.get(field)
            if nc is not None:
                return nc.kind == "date"
        return True  # no data: assume proper date mapping (seconds unit)

    def _ensure_num_sorted_all(self, field: str) -> None:
        """Upload the value-sort layout on every segment (local
        execution only — the mesh packs its own arrays)."""
        if not self.allow_device_topk:
            return
        from .executor import ensure_num_sorted
        for seg in self.segments:
            ensure_num_sorted(seg, field)

    def _extent(self, field: str) -> tuple[float, float, bool]:
        lo, hi, any_vals = np.inf, -np.inf, False
        is_int = True
        if field in self.extent_override:
            # entries are (lo, hi, is_int) — dtype comes from the pack
            # spec too, since hosts' local columns may disagree
            ov = self.extent_override[field]
            if ov is None:
                return 0.0, 0.0, True
            return float(ov[0]), float(ov[1]), bool(ov[2])
        for seg in self.segments:
            nc = seg.numerics.get(field)
            if nc is None:
                continue
            is_int = nc.values.dtype == np.int32
            # segments are immutable: cache the column extent — at 20M
            # rows the exists-masked copy below costs ~100ms of host
            # time PER SEARCH otherwise (it set the single-query p50)
            cache = getattr(seg, "_extent_cache", None)
            if cache is None:
                cache = {}
                seg._extent_cache = cache  # type: ignore[attr-defined]
            ext = cache.get(field, "miss")
            if ext == "miss":
                n = seg.num_docs
                if nc.mv_values is not None:
                    vals = nc.mv_values[nc.mv_exists]
                elif nc.exists[:n].all():
                    vals = nc.values[:n]  # view, no masked copy
                else:
                    vals = nc.values[: seg.capacity][nc.exists]
                ext = ((float(vals.min()), float(vals.max()))
                       if vals.size else None)
                cache[field] = ext
            if ext is None:
                continue
            any_vals = True
            lo = min(lo, ext[0])
            hi = max(hi, ext[1])
        if not any_vals:
            lo = hi = 0.0
        return lo, hi, is_int

    def build(self, specs: list[AggSpec]) -> tuple[tuple, list[tuple]]:
        """Returns (agg_desc, per-segment agg_params list)."""
        descs: list[tuple] = []
        per_seg: list[list] = [[] for _ in self.segments]
        for spec in specs:
            subs = tuple((s.name, s.field, s.kind) for s in spec.sub_metrics)
            if spec.kind == "terms":
                terms, seg_maps = self.global_ords[spec.field]
                n_global = next_pow2(len(terms), floor=1)
                # device-side shard_size cut (InternalTerms shard_size):
                # only for high-cardinality count-ordered requests —
                # small ordinal spaces download whole and stay exact
                top_s = 0
                if self.allow_device_topk and spec.size < (1 << 30) \
                        and spec.order[0] in ("_count", "doc_count") \
                        and spec.order[1] == "desc":
                    shard_size = int(spec.size * 1.5) + 10
                    if n_global > 2048 and shard_size * 4 < n_global:
                        top_s = shard_size
                descs.append((spec.name, ("terms_kw", spec.field,
                                          n_global, subs, top_s)))
                # static sort layout -> scatter-free device group-by
                # (the interpreter falls back per sub-metric where the
                # scatter path is still required)
                if self.allow_device_topk:
                    # local execution only: the mesh path packs its own
                    # arrays and never consults kw_sorted, so ensuring
                    # it there would pointlessly upload whole segments
                    # to the default device
                    from .executor import ensure_kw_sorted
                    for seg in self.segments:
                        if spec.field in seg.keywords \
                                and seg.keywords[spec.field].mv_ords \
                                is None:
                            ensure_kw_sorted(seg, spec.field)
                for i in range(len(self.segments)):
                    sm = seg_maps[i]
                    inv = np.full(n_global, -1, dtype=np.int32)
                    inv[sm] = np.arange(len(sm), dtype=np.int32)
                    per_seg[i].append((sm, inv))
            elif spec.kind == "cardinality":
                terms, seg_maps = self.global_ords[spec.field]
                if len(terms) > spec.precision_threshold:
                    # high cardinality: HLL++ sketch registers instead
                    # of an O(cardinality) exact count array
                    from ..ops.hll import M, term_registers
                    g_reg, g_rank = term_registers(terms)
                    self.hll_names.add(spec.name)
                    descs.append((spec.name,
                                  ("cardinality_hll", spec.field, M)))
                    for i in range(len(self.segments)):
                        sm = seg_maps[i]
                        safe = np.clip(sm, 0, max(len(terms) - 1, 0))
                        per_seg[i].append((g_reg[safe], g_rank[safe]))
                else:
                    n_global = next_pow2(len(terms), floor=1)
                    descs.append((spec.name,
                                  ("cardinality_kw", spec.field, n_global)))
                    for i in range(len(self.segments)):
                        per_seg[i].append((seg_maps[i],))
            elif spec.kind in ("date_histogram", "histogram"):
                lo, hi, is_int = self._extent(spec.field)
                if spec.kind == "date_histogram":
                    fixed = parse_interval_seconds(spec.interval)
                    unit = 1000 if self._is_date_column(spec.field) else 1
                    self.date_unit[spec.name] = unit
                    if fixed is not None and unit == 1:
                        fixed = fixed * 1000  # column is millis: scale interval
                else:
                    fixed = float(spec.interval)
                    if fixed <= 0:
                        raise SearchParseError("histogram interval must be > 0")
                if fixed is not None:
                    origin = np.floor(lo / fixed) * fixed
                    n_raw = int((hi - origin) // fixed) + 1 if hi >= origin else 1
                    n_buckets = next_pow2(n_raw, floor=1)
                    origin = int(origin) if is_int else origin
                    self.origins[spec.name] = (origin, fixed, n_raw)
                    descs.append((spec.name,
                                  ("hist_fixed", spec.field, n_buckets, subs)))
                    self._ensure_num_sorted_all(spec.field)
                    for i in range(len(self.segments)):
                        per_seg[i].append((np.asarray(origin), np.asarray(fixed)))
                else:  # calendar interval
                    unit = self.date_unit.get(spec.name, 1000)
                    lo_s = int(lo) if unit == 1000 else int(lo) // 1000
                    hi_s = int(hi) if unit == 1000 else int(hi) // 1000
                    edges = calendar_edges(lo_s, hi_s, str(spec.interval))
                    if unit == 1:
                        edges = edges * 1000  # back to column unit (millis)
                    self.edges[spec.name] = edges
                    n_raw = len(edges) - 1
                    n_buckets = next_pow2(max(n_raw, 1), floor=1)
                    padded = np.full(n_buckets + 1, np.iinfo(np.int32).max, np.int64)
                    padded[: len(edges)] = edges
                    descs.append((spec.name,
                                  ("hist_edges", spec.field, n_buckets, subs)))
                    for i in range(len(self.segments)):
                        per_seg[i].append((padded.astype(np.int32),))
            elif spec.kind == "value_count":
                kind = "value_count_kw" if any(
                    spec.field in s.keywords for s in self.segments) else "value_count_num"
                descs.append((spec.name, (kind, spec.field)))
                for i in range(len(self.segments)):
                    per_seg[i].append(())
            elif spec.kind in ("percentiles", "percentile_ranks"):
                lo, hi, _ = self._extent(spec.field)
                width = max((hi - lo) / _PCTL_BINS, 1e-9)
                self.origins[spec.name] = (lo, width, _PCTL_BINS)
                descs.append((spec.name, ("pctl", spec.field, _PCTL_BINS)))
                self._ensure_num_sorted_all(spec.field)
                for i in range(len(self.segments)):
                    per_seg[i].append((np.float32(lo), np.float32(width)))
            elif spec.kind in ("geo_bounds", "geo_centroid"):
                descs.append((spec.name, (spec.kind, spec.field)))
                for i in range(len(self.segments)):
                    per_seg[i].append(())
            elif spec.kind == "geohash_grid":
                # device returns the packed match bitmask; the grid cells
                # + counts reduce host-side (shard_partials) — bucket
                # cardinality is unbounded so it can't be a static
                # scatter target (ref: bucket/geogrid/GeoHashGrid)
                descs.append((spec.name, ("matchmask",)))
                for i in range(len(self.segments)):
                    per_seg[i].append(())
            elif spec.kind in METRIC_KINDS or spec.kind == "scripted_metric":
                if spec.field.startswith("_script\x00"):
                    tag = spec.field.split("\x00", 1)[1]
                    from ..script import compile_script
                    from .executor import ensure_script_vals
                    cs = compile_script(tag.split("\x00", 1)[0])
                    for s in self.segments:
                        ensure_script_vals(s, cs.fields)
                    descs.append((spec.name, ("stats_script", tag)))
                else:
                    descs.append((spec.name, ("stats", spec.field)))
                for i in range(len(self.segments)):
                    per_seg[i].append(())
            elif spec.kind in DERIVED_KINDS:
                raise SearchParseError(
                    f"derived aggregation [{spec.kind}] cannot build a "
                    f"device desc (route through the reader)")
            else:
                raise SearchParseError(f"unknown aggregation [{spec.kind}]")
        return tuple(descs), [tuple(p) for p in per_seg]


# ---------------------------------------------------------------------------
# Reduce: per-segment partial arrays -> response JSON (per batched query b)
# ---------------------------------------------------------------------------


def _acc_stats(partials: list[dict], name: str, key: str, how: str):
    """Like _acc but for partials shaped {name: {"stats": {key: [B]}}}."""
    arrays = [p[name]["stats"][key] for p in partials if name in p]
    out = np.asarray(arrays[0], dtype=np.float64).copy()
    for a in arrays[1:]:
        a = np.asarray(a, dtype=np.float64)
        if how == "sum":
            out += a
        elif how == "min":
            out = np.minimum(out, a)
        else:
            out = np.maximum(out, a)
    return out


def _geo_grid_accumulate(spec: AggSpec, segment: Segment,
                         mask_bytes: np.ndarray, buckets: dict) -> None:
    """One segment's contribution to a geohash_grid: unpack the device
    match bitmask, quantize matching points to grid cells, merge counts
    + sub-metric stats into `buckets` keyed by geohash string."""
    from ..ops.geo import geohash_cells, cell_to_geohash

    gc = segment.geos.get(spec.field)
    if gc is None:
        return
    mask = np.unpackbits(mask_bytes.astype(np.uint8),
                         bitorder="little")[: segment.capacity].astype(bool)
    sel = mask & gc.exists
    if not sel.any():
        return
    cells = geohash_cells(gc.lat[sel], gc.lon[sel], spec.precision)
    uniq, inverse, counts = np.unique(cells, return_inverse=True,
                                      return_counts=True)
    sub_stats: dict[str, dict[str, np.ndarray]] = {}
    for sm in spec.sub_metrics:
        nc = segment.numerics.get(sm.field)
        n_u = len(uniq)
        entry: dict[str, np.ndarray] = {
            "count": np.zeros(n_u), "sum": np.zeros(n_u),
            "min": np.full(n_u, np.inf), "max": np.full(n_u, -np.inf),
            "sum_sq": np.zeros(n_u)}
        if nc is not None:
            if nc.mv_raw is not None:   # every value contributes
                val_cols = [(nc.mv_raw[:, m], nc.mv_exists[:, m])
                            for m in range(nc.mv_raw.shape[1])]
            else:
                val_cols = [(nc.raw, nc.exists)]
            for raw_col, ex_col in val_cols:
                vals = raw_col[sel].astype(np.float64)
                has = ex_col[sel]
                entry["count"] += np.bincount(inverse[has],
                                              minlength=n_u).astype(float)
                entry["sum"] += np.bincount(inverse[has],
                                            weights=vals[has],
                                            minlength=n_u)
                entry["sum_sq"] += np.bincount(inverse[has],
                                               weights=vals[has] ** 2,
                                               minlength=n_u)
                np.minimum.at(entry["min"], inverse[has], vals[has])
                np.maximum.at(entry["max"], inverse[has], vals[has])
        sub_stats[sm.name] = entry
    for u, cell in enumerate(uniq):
        key = cell_to_geohash(int(cell), spec.precision)
        cur = buckets.get(key)
        if cur is None:
            cur = buckets[key] = {"count": 0, "subs": {}}
        cur["count"] += int(counts[u])
        for sm in spec.sub_metrics:
            tgt = cur["subs"].setdefault(
                sm.name, {"count": 0.0, "sum": 0.0, "min": np.inf,
                          "max": -np.inf, "sum_sq": 0.0})
            e = sub_stats[sm.name]
            tgt["count"] += float(e["count"][u])
            tgt["sum"] += float(e["sum"][u])
            tgt["sum_sq"] += float(e["sum_sq"][u])
            tgt["min"] = min(tgt["min"], float(e["min"][u]))
            tgt["max"] = max(tgt["max"], float(e["max"][u]))


def _acc(partials: list[dict], name: str, key: str, how: str = "sum"):
    arrays = [p[name][key] for p in partials if name in p]
    out = np.asarray(arrays[0], dtype=np.float64).copy()
    for a in arrays[1:]:
        a = np.asarray(a, dtype=np.float64)
        if how == "sum":
            out += a
        elif how == "min":
            out = np.minimum(out, a)
        elif how == "max":
            out = np.maximum(out, a)
    return out


def shard_partials(specs: list[AggSpec], ctx: ShardAggContext,
                   partials: list[dict], batch: int) -> list[dict]:
    """Reduce per-SEGMENT device arrays into per-query SHARD partials keyed
    by bucket key (term string / epoch-sec / numeric key) so that shards
    with different ordinal spaces or histogram extents can merge.

    Partial shapes per agg name:
      terms/cardinality: {"buckets": {key: {"count": c, "subs": {n: stats}}}}
      (date_)histogram:  same with numeric keys
      metrics:           {"stats": {count,sum,min,max[,sum_sq]}}
    """
    out: list[dict] = [dict() for _ in range(batch)]
    for spec in specs:
        name = spec.name
        if spec.kind == "cardinality" and name in ctx.hll_names:
            regs = _acc(partials, name, "max", how="max")     # [B, M]
            for b in range(batch):
                out[b][name] = {"hll": regs[b]}
            continue
        if spec.kind == "terms" and any(
                "top_idx" in p.get(name, {}) for p in partials):
            # device-compressed per-segment tops (executor._compress_topk)
            terms, _ = ctx.global_ords[spec.field]
            seg_entries = [p[name] for p in partials if name in p]
            sub_keys = [k for k in seg_entries[0]
                        if k.startswith("sub\x00")]
            for b in range(batch):
                buckets: dict = {}
                total = 0.0
                for e in seg_entries:
                    idx = np.asarray(e["top_idx"][b])
                    cnt = np.asarray(e["top_counts"][b])
                    total += float(np.asarray(e["total"][b])[0])
                    for j in range(len(idx)):
                        c = float(cnt[j])
                        if c <= 0:
                            continue
                        g = int(idx[j])
                        if g >= len(terms):
                            continue
                        cur = buckets.setdefault(
                            terms[g], {"count": 0, "subs": {}})
                        cur["count"] += int(round(c))
                        for sk in sub_keys:
                            _, mname, skey = sk.split("\x00")
                            st = cur["subs"].setdefault(mname, {})
                            v = float(np.asarray(e[sk][b][j]))
                            if skey == "min":
                                st[skey] = min(st.get(skey, v), v)
                            elif skey == "max":
                                st[skey] = max(st.get(skey, v), v)
                            else:
                                st[skey] = st.get(skey, 0.0) + v
                out[b][name] = {"buckets": buckets, "total": total}
            continue
        if spec.kind in ("terms", "cardinality"):
            terms, _ = ctx.global_ords[spec.field]
            counts = _acc(partials, name, "counts")           # [B, G]
            sub_acc = _reduce_subs(spec, partials, name)
            # shard-level truncation (ref: InternalTerms shard_size =
            # size*1.5+10 — the reduce only needs each shard's top
            # buckets by the order key; cardinality must stay exact)
            shard_size = None
            if spec.kind == "terms" and spec.size < (1 << 30):
                shard_size = int(spec.size * 1.5) + 10
            for b in range(batch):
                row = counts[b][: len(terms)]
                nz = np.nonzero(row > 0)[0]
                total = float(row.sum())
                if shard_size is not None and len(nz) > shard_size:
                    okey, odir = spec.order
                    if okey in ("_count", "doc_count"):
                        sel = nz[np.argpartition(-row[nz],
                                                 shard_size)[:shard_size]]
                    elif okey == "_term":
                        # global ords follow term order
                        sel = (nz[-shard_size:] if odir == "desc"
                               else nz[:shard_size])
                    else:
                        sel = nz  # sub-metric order: keep everything
                    nz = np.sort(sel)
                buckets = {}
                for g in nz:
                    buckets[terms[g]] = {
                        "count": int(row[g]),
                        "subs": _sub_stats(spec, sub_acc, b, g)}
                entry = {"buckets": buckets}
                if spec.kind == "terms":
                    entry["total"] = total
                out[b][name] = entry
        elif spec.kind in ("date_histogram", "histogram"):
            counts = _acc(partials, name, "counts")
            sub_acc = _reduce_subs(spec, partials, name)
            if name in ctx.origins:
                origin, interval, n_raw = ctx.origins[name]
                keys = [origin + i * interval for i in range(n_raw)]
            else:
                edges = ctx.edges[name]
                keys = [int(e) for e in edges[:-1]]
                n_raw = len(keys)
            if spec.kind == "date_histogram":
                unit = ctx.date_unit.get(name, 1000)
                keys = [int(k) * unit for k in keys]  # normalize to millis
            for b in range(batch):
                buckets = {}
                for i in range(n_raw):
                    c = int(counts[b][i])
                    if c > 0:
                        buckets[keys[i]] = {
                            "count": c, "subs": _sub_stats(spec, sub_acc, b, i)}
                out[b][name] = {"buckets": buckets}
        elif spec.kind == "value_count":
            counts = _acc(partials, name, "count")
            for b in range(batch):
                out[b][name] = {"stats": {"count": float(counts[b])}}
        elif spec.kind in ("percentiles", "percentile_ranks"):
            counts = _acc(partials, name, "counts")      # [B, bins]
            lo, width, n_bins = ctx.origins[name]
            centers = [lo + (i + 0.5) * width for i in range(n_bins)]
            for b in range(batch):
                points = {}
                row = counts[b]
                for i in np.nonzero(row > 0)[0]:
                    points[centers[int(i)]] = points.get(
                        centers[int(i)], 0.0) + float(row[int(i)])
                out[b][name] = {"points": points}
        elif spec.kind in ("geo_bounds", "geo_centroid"):
            sample = partials[0][name]["stats"]
            stats = {}
            for key in sample:
                how = ("min" if key.startswith("min") else
                       "max" if key.startswith("max") else "sum")
                stats[key] = _acc_stats(partials, name, key, how)
            for b in range(batch):
                out[b][name] = {"stats": {k: float(v[b])
                                          for k, v in stats.items()}}
        elif spec.kind == "geohash_grid":
            for b in range(batch):
                buckets: dict = {}
                for si, part in enumerate(partials):
                    if name not in part:
                        continue
                    _geo_grid_accumulate(
                        spec, ctx.segments[si],
                        np.asarray(part[name]["mask"][b]), buckets)
                out[b][name] = {"buckets": buckets}
        elif spec.kind in METRIC_KINDS or spec.kind == "scripted_metric":
            stats = {
                "count": _acc(partials, name, "count"),
                "sum": _acc(partials, name, "sum"),
                "min": _acc(partials, name, "min", "min"),
                "max": _acc(partials, name, "max", "max"),
            }
            if spec.kind == "extended_stats":
                stats["sum_sq"] = _acc(partials, name, "sum_sq")
            for b in range(batch):
                out[b][name] = {"stats": {k: float(v[b]) for k, v in stats.items()}}
    return out


def _sub_stats(spec: AggSpec, sub_acc: dict, b: int, g: int) -> dict:
    subs = {}
    for sm in spec.sub_metrics:
        subs[sm.name] = {k: float(v[b][g]) for k, v in sub_acc[sm.name].items()}
    return subs


def merge_shard_partials(specs: list[AggSpec], parts: list[dict]) -> dict:
    """Merge shard partials for ONE query — InternalAggregation.reduce."""
    merged: dict = {}
    for spec in specs:
        name = spec.name
        entries = [p[name] for p in parts if name in p]
        if not entries:
            continue
        if any("hll" in e for e in entries):
            # shards may disagree on exact-vs-sketch (the switch is a
            # per-shard term-count decision): exact bucket partials
            # CONVERT to sketch registers (hash their keys) so skewed
            # shards still merge — ref: HyperLogLogPlusPlus upgrading
            # linear counting to HLL on merge
            from ..ops.hll import M as _HLL_M, term_registers
            regs = np.zeros(_HLL_M, dtype=np.float64)
            for e in entries:
                if "hll" in e:
                    regs = np.maximum(regs, np.asarray(e["hll"]))
                else:
                    keys = list(e["buckets"])
                    r_idx, r_rank = term_registers(keys, memo=False)
                    if keys:
                        np.maximum.at(regs, r_idx[: len(keys)],
                                      r_rank[: len(keys)])
            merged[name] = {"hll": regs}
        elif "points" in entries[0]:
            points: dict = {}
            for e in entries:
                for c, n in e["points"].items():
                    points[c] = points.get(c, 0.0) + n
            merged[name] = {"points": points}
        elif "derived" in entries[0]:
            merged[name] = {"derived": merge_derived(spec, entries)}
        elif "buckets" in entries[0]:
            buckets: dict = {}
            for e in entries:
                for key, bk in e["buckets"].items():
                    cur = buckets.get(key)
                    if cur is None:
                        buckets[key] = {"count": bk["count"],
                                        "subs": {n: dict(s) for n, s in bk["subs"].items()}}
                    else:
                        cur["count"] += bk["count"]
                        for n, s in bk["subs"].items():
                            tgt = cur["subs"][n]
                            for k, v in s.items():
                                if k == "min":
                                    tgt[k] = min(tgt[k], v)
                                elif k == "max":
                                    tgt[k] = max(tgt[k], v)
                                else:
                                    tgt[k] += v
            merged[name] = {"buckets": buckets}
            if any("total" in e for e in entries):
                merged[name]["total"] = sum(e.get("total", 0.0)
                                            for e in entries)
        else:
            stats: dict = {}
            for e in entries:
                for k, v in e["stats"].items():
                    if k not in stats:
                        stats[k] = v
                    elif k.startswith("min"):     # min / min_lat / min_lon
                        stats[k] = min(stats[k], v)
                    elif k.startswith("max"):
                        stats[k] = max(stats[k], v)
                    else:
                        stats[k] += v
            merged[name] = {"stats": stats}
    return merged


def merge_derived(spec: AggSpec, entries: list[dict]) -> dict:
    """Cross-shard reduce of a derived agg: counts sum, nested partials
    merge recursively, top hits re-rank."""
    nested = parse_aggs(spec.sub_raw)
    out: dict = {}
    for key, _q, _extra in spec.buckets:
        parts = [e["derived"][key] for e in entries
                 if key in e.get("derived", {})]
        if not parts:
            continue
        bucket = {"count": sum(p["count"] for p in parts)}
        if nested:
            bucket["sub"] = merge_shard_partials(
                nested, [p.get("sub", {}) for p in parts])
        hits = [h for p in parts for h in p.get("hits", [])]
        if hits or spec.kind == "top_hits":
            hits.sort(key=lambda h: -(h.get("_score") or 0.0))
            bucket["hits"] = hits[: spec.top_hits_size]
        out[key] = bucket
    return out


def finalize_derived(spec: AggSpec, merged_buckets: dict) -> dict:
    nested = parse_aggs(spec.sub_raw)

    def bucket_json(key):
        b = merged_buckets.get(key)
        if b is None:
            return {"doc_count": 0}
        out = {"doc_count": int(b["count"])}
        if nested:
            out.update(finalize_partials(nested, b.get("sub", {})))
        if "hits" in b:
            out["hits"] = {"total": int(b["count"]),
                           "hits": b["hits"]}
        return out

    if spec.kind == "significant_terms":
        def totals(key):
            b = merged_buckets.get(key)
            if b is None:
                return 0, []
            fin = finalize_partials(nested, b.get("sub", {}))
            return (int(b["count"]),
                    fin.get("__sig_terms", {}).get("buckets", []))

        fg_t, fg_b = totals("fg")
        bg_t, bg_b = totals("bg")
        return significant_buckets(spec, fg_t, fg_b, bg_t, bg_b)
    if spec.kind in ("filter", "missing", "global", "nested",
                     "reverse_nested", "children"):
        key = spec.buckets[0][0]
        return bucket_json(key)
    if spec.kind == "top_hits":
        key = spec.buckets[0][0]
        b = merged_buckets.get(key) or {"count": 0, "hits": []}
        return {"hits": {"total": int(b["count"]),
                         "max_score": (b["hits"][0].get("_score")
                                       if b.get("hits") else None),
                         "hits": b.get("hits", [])}}
    if spec.kind == "filters":
        return {"buckets": {key: bucket_json(key)
                            for key, _q, _x in spec.buckets}}
    # range / date_range: ordered array with from/to echoes
    buckets = []
    for key, _q, extra in spec.buckets:
        bj = bucket_json(key)
        entry = {"key": key, **{k: v for k, v in extra.items()
                                if v is not None}, **bj}
        buckets.append(entry)
    return {"buckets": buckets}


def percentile_rank_values(points: dict, values: tuple) -> dict:
    """Inverse of percentile_values: % of observed weight at or below each
    value (ref: metrics/percentiles/PercentileRanks)."""
    items = sorted(points.items())
    total = sum(c for _, c in items)
    out = {}
    for v in values:
        key = str(float(v))
        if total == 0:
            out[key] = None
            continue
        below = sum(c for x, c in items if x <= v)
        out[key] = 100.0 * below / total
    return out


def jlh_score(fg_count: float, fg_total: float, bg_count: float,
              bg_total: float) -> float:
    """JLH significance heuristic (ref: bucket/significant/heuristics/
    JLHScore.java): (fgPct - bgPct) * (fgPct / bgPct), 0 when not more
    frequent in the foreground."""
    if fg_total <= 0 or bg_total <= 0 or bg_count <= 0:
        return 0.0
    fg_pct = fg_count / fg_total
    bg_pct = bg_count / bg_total
    if fg_pct <= bg_pct:
        return 0.0
    return (fg_pct - bg_pct) * (fg_pct / bg_pct)


def apply_sig_subs(agg_specs, aggregations: dict, readers: list,
                   raw_query: dict | None = None,
                   search_ids=None) -> None:
    """Stitch significant_terms sub-aggs into parent terms buckets.

    Shared by the single-reader path (ShardReader) and the node-level
    multi-shard path. Foreground = enclosing query AND bucket term: when
    the request has a real query, `search_ids(query_dict) -> set[str]`
    supplies the matching doc ids (capped by the caller) and
    sig_term_counts intersects with them. Ref:
    SignificantTermsAggregatorFactory under a parent bucket collector.
    """
    for spec in agg_specs:
        subs = getattr(spec, "sig_subs", None)
        if spec.kind != "terms" or not subs:
            continue
        agg_out = (aggregations or {}).get(spec.name)
        if not agg_out:
            continue
        allowed = None
        if raw_query is not None and search_ids is not None \
                and raw_query != {"match_all": {}}:
            allowed = search_ids(raw_query)
        for sname, conf in subs.items():
            field = conf.get("field")
            sub_spec = AggSpec(
                name=sname, kind="significant_terms", field=field,
                size=int(conf.get("size", 10) or 10),
                min_doc_count=int(conf.get("min_doc_count", 3)))

            def summed(flt_value=None, _f=field):
                total = 0
                counts: dict = {}
                for reader in readers:
                    t, c = reader.sig_term_counts(
                        _f, spec.field if flt_value is not None else None,
                        flt_value,
                        allowed_ids=(allowed if flt_value is not None
                                     else None))
                    total += t
                    for k, v in c.items():
                        counts[k] = counts.get(k, 0) + v
                return total, [{"key": k, "doc_count": v}
                               for k, v in counts.items()]

            bg_total, bg_counts = summed()
            for bucket in agg_out.get("buckets", []):
                fg_total, fg_counts = summed(bucket["key"])
                bucket[sname] = significant_buckets(
                    sub_spec, fg_total, fg_counts, bg_total, bg_counts)


def _decimal_format(pattern: str, value: float) -> str:
    """Tiny Java DecimalFormat subset for histogram `format` patterns:
    literal prefix/suffix around a #/0 number mask, decimals = digits
    after '.' in the mask (ref: ValueFormatter.Number.Pattern)."""
    import re as _re
    m = _re.search(r"[#0][#0,.]*", pattern)
    if not m:
        return pattern
    mask = m.group(0)
    decimals = len(mask.split(".", 1)[1]) if "." in mask else 0
    num = f"{value:.{decimals}f}"
    return pattern[: m.start()] + num + pattern[m.end():]


def significant_buckets(spec: AggSpec, fg_total: int, fg_buckets: list,
                        bg_total: int, bg_buckets: list) -> dict:
    """Combine foreground/background term counts into significant-terms
    buckets ranked by JLH score."""
    bg_counts = {b["key"]: b["doc_count"] for b in bg_buckets}
    out = []
    for b in fg_buckets:
        fg_c = b["doc_count"]
        if fg_c < spec.min_doc_count:
            continue
        bg_c = bg_counts.get(b["key"], fg_c)
        score = jlh_score(fg_c, fg_total, bg_c, bg_total)
        if score <= 0:
            continue
        out.append({"key": b["key"], "doc_count": fg_c,
                    "score": score, "bg_count": bg_c})
    out.sort(key=lambda x: (-x["score"], x["key"]))
    return {"doc_count": fg_total, "buckets": out[: spec.size]}


def percentile_values(points: dict, percents: tuple) -> dict:
    """Weighted points -> percentile values by t-digest-style centroid
    interpolation (each histogram bin acts as a centroid whose mass sits
    at its center; quantiles interpolate linearly between adjacent
    centroid mid-ranks — ref: metrics/percentiles/tdigest/
    TDigestState.quantile)."""
    if not points:
        return {str(p): None for p in percents}
    items = sorted(points.items())
    total = sum(c for _, c in items)
    # cumulative mid-rank of each centroid
    mids: list[tuple[float, float]] = []
    cum = 0.0
    for center, cnt in items:
        mids.append((cum + cnt / 2.0, float(center)))
        cum += cnt
    out = {}
    for p in percents:
        target = total * p / 100.0
        if target <= mids[0][0]:
            out[str(p)] = mids[0][1]
            continue
        if target >= mids[-1][0]:
            out[str(p)] = mids[-1][1]
            continue
        val = mids[-1][1]
        for j in range(1, len(mids)):
            r0, v0 = mids[j - 1]
            r1, v1 = mids[j]
            if target <= r1:
                frac = (target - r0) / (r1 - r0) if r1 > r0 else 0.0
                val = v0 + frac * (v1 - v0)
                break
        out[str(p)] = float(val)
    return out


def _stats_json(kind: str, s: dict) -> dict:
    count = s.get("count", 0.0)
    if kind == "sum":
        return {"value": s.get("sum", 0.0)}
    if kind == "value_count":
        return {"value": int(count)}
    if kind == "min":
        v = s.get("min", np.inf)
        return {"value": None if np.isinf(v) else v}
    if kind == "max":
        v = s.get("max", -np.inf)
        return {"value": None if np.isinf(v) else v}
    if kind == "avg":
        return {"value": (s.get("sum", 0.0) / count) if count else None}
    out = {
        "count": int(count),
        "min": None if count == 0 else s.get("min"),
        "max": None if count == 0 else s.get("max"),
        "sum": s.get("sum", 0.0),
        "avg": (s.get("sum", 0.0) / count) if count else None,
    }
    if kind == "extended_stats":
        ssq = s.get("sum_sq", 0.0)
        out["sum_of_squares"] = ssq
        if count:
            mean = out["avg"]
            var = max(ssq / count - mean * mean, 0.0)
            out["variance"] = var
            out["std_deviation"] = float(np.sqrt(var))
        else:
            out["variance"] = None
            out["std_deviation"] = None
    return out


def finalize_partials(specs: list[AggSpec], merged: dict) -> dict:
    """Merged partials -> response JSON (ordering, size, min_doc_count)."""
    response: dict = {}
    for spec in specs:
        name = spec.name
        if name not in merged:
            if spec.kind in ("terms",):
                response[name] = {"doc_count_error_upper_bound": 0,
                                  "sum_other_doc_count": 0, "buckets": []}
            elif spec.kind in ("date_histogram", "histogram"):
                response[name] = {"buckets": []}
            elif spec.kind == "cardinality":
                response[name] = {"value": 0}
            elif spec.kind == "geo_bounds":
                response[name] = {}
            elif spec.kind == "geo_centroid":
                response[name] = {"count": 0}
            elif spec.kind == "geohash_grid":
                response[name] = {"buckets": []}
            elif spec.kind == "percentiles":
                response[name] = {"values": percentile_values(
                    {}, spec.percents)}
            elif spec.kind == "percentile_ranks":
                response[name] = {"values": percentile_rank_values(
                    {}, spec.percents)}
            elif spec.kind == "scripted_metric":
                response[name] = {"value": 0.0}
            elif spec.kind in DERIVED_KINDS:
                response[name] = finalize_derived(spec, {})
            else:
                response[name] = _stats_json(spec.kind, {"count": 0.0})
            continue
        entry = merged[name]
        if spec.kind == "percentiles":
            response[name] = {"values": percentile_values(
                entry["points"], spec.percents)}
        elif spec.kind == "percentile_ranks":
            response[name] = {"values": percentile_rank_values(
                entry["points"], spec.percents)}
        elif spec.kind == "scripted_metric":
            response[name] = {"value": entry["stats"].get("sum", 0.0)}
        elif spec.kind in DERIVED_KINDS:
            response[name] = finalize_derived(spec, entry["derived"])
        elif spec.kind == "cardinality":
            if "hll" in entry:
                from ..ops.hll import estimate
                response[name] = {"value": int(round(
                    estimate(entry["hll"])))}
            else:
                response[name] = {"value": len(entry["buckets"])}
        elif spec.kind == "geo_bounds":
            s = entry["stats"]
            if s.get("count", 0) <= 0:
                response[name] = {}
            else:
                response[name] = {"bounds": {
                    "top_left": {"lat": s["max_lat"], "lon": s["min_lon"]},
                    "bottom_right": {"lat": s["min_lat"],
                                     "lon": s["max_lon"]}}}
        elif spec.kind == "geo_centroid":
            s = entry["stats"]
            count = s.get("count", 0)
            if count <= 0:
                response[name] = {"count": 0}
            else:
                response[name] = {
                    "location": {"lat": s["sum_lat"] / count,
                                 "lon": s["sum_lon"] / count},
                    "count": int(count)}
        elif spec.kind == "geohash_grid":
            items = sorted(entry["buckets"].items(),
                           key=lambda kv: (-kv[1]["count"], kv[0]))
            buckets = []
            for key, bk in items[: spec.size]:
                bucket = {"key": key, "doc_count": bk["count"]}
                for sm in spec.sub_metrics:
                    bucket[sm.name] = _stats_json(
                        sm.kind, bk["subs"].get(sm.name, {"count": 0.0}))
                buckets.append(bucket)
            response[name] = {"buckets": buckets}
        elif spec.kind == "terms":
            items = [(key, bk) for key, bk in entry["buckets"].items()
                     if bk["count"] >= max(spec.min_doc_count, 1)]
            order_key, order_dir = spec.order
            reverse = order_dir == "desc"
            if order_key == "_term":
                items.sort(key=lambda kv: kv[0], reverse=reverse)
            elif order_key in ("_count", "doc_count"):
                items.sort(key=lambda kv: kv[0])
                items.sort(key=lambda kv: kv[1]["count"], reverse=reverse)
            else:
                sub_name = order_key.split(".")[0]
                sub = next((s for s in spec.sub_metrics if s.name == sub_name),
                           None)
                if sub is None:
                    raise SearchParseError(
                        f"unknown terms order key [{order_key}]")
                items.sort(key=lambda kv: kv[0])
                items.sort(key=lambda kv: _stats_json(
                    sub.kind, kv[1]["subs"][sub.name]).get("value") or 0.0,
                    reverse=reverse)
            # shard partials are truncated to shard_size, so the true
            # doc total rides alongside the kept buckets
            total = int(entry.get("total",
                                  sum(bk["count"]
                                      for _, bk in entry["buckets"].items())))
            top = items[: spec.size]
            buckets = []
            for key, bk in top:
                bucket = {"key": key, "doc_count": bk["count"]}
                for sm in spec.sub_metrics:
                    bucket[sm.name] = _stats_json(sm.kind, bk["subs"][sm.name])
                buckets.append(bucket)
            response[name] = {
                "doc_count_error_upper_bound": 0,
                "sum_other_doc_count": total - sum(b["doc_count"] for b in buckets),
                "buckets": buckets,
            }
        elif spec.kind in ("date_histogram", "histogram"):
            is_date = spec.kind == "date_histogram"
            buckets = []
            for key in sorted(entry["buckets"]):
                bk = entry["buckets"][key]
                if bk["count"] < spec.min_doc_count:
                    continue
                if is_date:
                    millis = int(key)  # partial keys are normalized millis
                    bucket = {"key": millis,
                              "key_as_string": format_date_millis(millis),
                              "doc_count": bk["count"]}
                else:
                    bucket = {"key": float(key), "doc_count": bk["count"]}
                    if spec.fmt:
                        bucket["key_as_string"] = _decimal_format(
                            spec.fmt, float(key))
                for sm in spec.sub_metrics:
                    bucket[sm.name] = _stats_json(sm.kind, bk["subs"][sm.name])
                buckets.append(bucket)
            response[name] = {"buckets": buckets}
        else:
            response[name] = _stats_json(spec.kind, entry["stats"])
    return response


def reduce_aggs(specs: list[AggSpec], ctx: ShardAggContext,
                partials: list[dict], batch: int) -> list[dict]:
    """Single-shard convenience: segment partials -> final response JSON."""
    per_query = shard_partials(specs, ctx, partials, batch)
    return [finalize_partials(specs, merge_shard_partials(specs, [p]))
            for p in per_query]


def _reduce_subs(spec: AggSpec, partials: list[dict], name: str) -> dict:
    out = {}
    for sm in spec.sub_metrics:
        entry = {}
        sample = partials[0][name].get(sm.name, {})
        for key in sample:
            how = "min" if key == "min" else "max" if key == "max" else "sum"
            entry[key] = _acc_nested(partials, name, sm.name, key, how)
        out[sm.name] = entry
    return out


def _acc_nested(partials, name, sub, key, how):
    arrays = [p[name][sub][key] for p in partials]
    out = np.asarray(arrays[0], dtype=np.float64).copy()
    for a in arrays[1:]:
        a = np.asarray(a, dtype=np.float64)
        out = out + a if how == "sum" else (
            np.minimum(out, a) if how == "min" else np.maximum(out, a))
    return out
