"""Adaptive micro-batching for the device search path.

The TPU serves one program at a time and (behind a remote-device
tunnel) charges a flat per-dispatch round trip, so the serving shape
that wins is FEW LARGE programs — the opposite of the reference's
many-independent-searcher-threads model (search/SearchService.java).

This coalescer turns concurrent searches against the same point-in-time
reader into one msearch device program with zero idle latency:

  * a lone request finds the leader lock free, executes immediately;
  * requests arriving while a program is in flight queue up; whoever
    finds the lock taken waits, and the next leader drains the WHOLE
    queue as one batch — batch size adapts to the arrival rate, no
    timer, no configured window.

The engine's per-request dispatch overhead amortizes across everything
that queued (bench.py measures ~65ms/dispatch on the dev tunnel vs
~0.5ms/query device compute at 20M rows — a 100-deep coalesced batch
is the difference between 15 QPS and 1300 QPS of agg traffic on ONE
chip)."""

from __future__ import annotations

import threading
from concurrent.futures import Future


class MicroBatcher:
    """One per ShardReader (point-in-time view); see module docstring."""

    def __init__(self, reader):
        self.reader = reader
        self._leader = threading.Lock()
        self._mx = threading.Lock()
        self._pending: list[tuple[dict, bool, Future]] = []

    def submit(self, body: dict, with_partials: bool = False) -> dict:
        fut: Future = Future()
        with self._mx:
            self._pending.append((body, with_partials, fut))
        if self._leader.acquire(blocking=False):
            try:
                self._drain()
            finally:
                self._leader.release()
        elif not fut.done():
            # a leader is mid-flight; it either picks us up in its next
            # drain round or finished just before our enqueue — in that
            # case lead the next round ourselves
            with self._leader:
                self._drain()
        return fut.result()

    def _drain(self) -> None:
        while True:
            with self._mx:
                batch = self._pending
                self._pending = []
            if not batch:
                return
            for wp in (False, True):
                group = [(b, f) for b, w, f in batch if w == wp]
                if not group:
                    continue
                try:
                    rs = self.reader.msearch([b for b, _f in group],
                                             with_partials=wp)
                    for (_b, f), r in zip(group, rs):
                        if not f.done():
                            f.set_result(r)
                except Exception:  # noqa: BLE001
                    # msearch parses all bodies up front, so ONE
                    # malformed query fails the whole program — retry
                    # each request alone so only the bad one errors
                    # (batch-mates must not inherit a stranger's 400)
                    for b, f in group:
                        if f.done():
                            continue
                        try:
                            f.set_result(self.reader.msearch(
                                [b], with_partials=wp)[0])
                        except Exception as e:  # noqa: BLE001
                            f.set_exception(e)


_ATTACH_LOCK = threading.Lock()


def coalesced_msearch(reader, body: dict,
                      with_partials: bool = False) -> dict:
    """Run one search through the reader's coalescer (attached lazily —
    readers are per-refresh-generation, so batchers die with them)."""
    b = getattr(reader, "_microbatcher", None)
    if b is None:
        with _ATTACH_LOCK:
            b = getattr(reader, "_microbatcher", None)
            if b is None:
                b = MicroBatcher(reader)
                reader._microbatcher = b
    return b.submit(body, with_partials)
