"""Search templates: mustache-style parameter substitution.

Reference analog: the Mustache script engine
(script/mustache/MustacheScriptEngineService.java) used by
RestSearchTemplateAction and index/query/TemplateQueryParser.java. The
subset implemented covers the template forms the rest-api-spec exercises:
{{var}} substitution (string interpolation or whole-value when the
placeholder is the entire string), {{#toJson}}var{{/toJson}}, and
{{#section}}...{{/section}} conditionals over truthy params.
"""

from __future__ import annotations

import json
import re

_VAR_RE = re.compile(r"\{\{([^{}#/^]+?)\}\}")
_TOJSON_RE = re.compile(r"\{\{#toJson\}\}\s*(.+?)\s*\{\{/toJson\}\}")
_SECTION_RE = re.compile(r"\{\{([#^])([^{}]+?)\}\}(.*?)\{\{/\2\}\}", re.S)


def _lookup(params: dict, path: str):
    cur = params
    for part in path.strip().split("."):
        if isinstance(cur, dict) and part in cur:
            cur = cur[part]
        else:
            return None
    return cur


def render_string(template: str, params: dict) -> str:
    """Render a template string to a string (values JSON-encoded when not
    plain scalars)."""

    def do_sections(text: str) -> str:
        def sub(m: re.Match) -> str:
            kind, name, body = m.group(1), m.group(2), m.group(3)
            val = _lookup(params, name)
            truthy = bool(val) and val not in (0, "")
            if kind == "^":
                return do_sections(body) if not truthy else ""
            if not truthy:
                return ""
            if isinstance(val, list):
                return "".join(do_sections(_VAR_RE.sub(
                    lambda mm: _fmt(item if mm.group(1).strip() == "."
                                    else _lookup(params, mm.group(1))), body))
                    for item in val)
            return do_sections(body)
        return _SECTION_RE.sub(sub, text)

    def _fmt(v) -> str:
        if v is None:
            return ""
        if isinstance(v, bool):
            return "true" if v else "false"
        if isinstance(v, str):
            # JSON-escape embedded quotes/backslashes — mustache in the
            # reference escapes for the JSON context
            # (JsonEscapingMustacheFactory)
            return json.dumps(v)[1:-1]
        if isinstance(v, (int, float)):
            return str(v)
        return json.dumps(v)

    text = _TOJSON_RE.sub(lambda m: json.dumps(_lookup(params, m.group(1))),
                          template)
    text = do_sections(text)
    return _VAR_RE.sub(lambda m: _fmt(_lookup(params, m.group(1))), text)


def render_template(template, params: dict):
    """Render a template (dict | JSON string) into a parsed JSON value.

    Dict form: placeholders inside string values are substituted; a string
    value that is exactly "{{var}}" is replaced by the param's native
    value (so sizes stay ints and arrays stay arrays).
    """
    params = params or {}
    if isinstance(template, str):
        rendered = render_string(template, params)
        return json.loads(rendered)

    def walk(node):
        if isinstance(node, dict):
            # keys can carry placeholders too ("match_{{template}}")
            return {(render_string(k, params) if "{{" in k else k): walk(v)
                    for k, v in node.items()}
        if isinstance(node, list):
            return [walk(v) for v in node]
        if isinstance(node, str):
            m = _VAR_RE.fullmatch(node)
            if m:
                val = _lookup(params, m.group(1))
                return val if val is not None else node
            return render_string(node, params)
        return node

    return walk(template)
