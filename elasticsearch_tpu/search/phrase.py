"""Positional matching: phrases and spans over the postings position sidecar.

Reference analog: Lucene PhraseQuery / SloppyPhraseScorer and the span
package (SpanTermQuery, SpanNearQuery, SpanFirstQuery, SpanOrQuery,
SpanNotQuery) as exposed by the reference's query parsers
(index/query/MatchQueryParser.java phrase mode, SpanTermQueryParser.java,
SpanNearQueryParser.java, SpanFirstQueryParser.java, SpanOrQueryParser.java,
SpanNotQueryParser.java).

Design: positional matching is irregular (ragged per-doc position lists)
and rare on the hot path, so it runs host-side at BIND time, vectorized
with numpy where the structure allows:

  * exact phrases use encoded (doc*stride + pos) sorted-set intersection —
    one np.intersect1d per phrase term, no per-doc loop at all;
  * sloppy phrases / span-near fall back to a per-candidate-doc pointer
    sweep (candidate sets are already small: conjunction of doc lists).

The result is a (docs, freqs) pair that the executor scores on device as a
precomputed posting list ("docs_w" bound) with eager BM25 impacts — the
same scatter-add path as ordinary terms, so phrase scoring costs the
device nothing extra.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ..index.segment import (PostingsField, BM25_K1, BM25_B, bm25_idf,
                             bm25_norms)


def _stride(pf: PostingsField) -> int:
    max_len = int(pf.doc_len.max(initial=0.0))
    return max(max_len + 2, 2)


def _enc_union(pf: PostingsField, tids: list[int], stride: int) -> np.ndarray:
    """Encoded positions of any of `tids` (union), sorted."""
    parts = [pf.enc_positions(t, stride) for t in tids]
    parts = [p for p in parts if p.size]
    if not parts:
        return np.empty(0, dtype=np.int64)
    if len(parts) == 1:
        return parts[0]
    return np.unique(np.concatenate(parts))


def phrase_match(pf: PostingsField, tid_groups: list[list[int]],
                 slop: int = 0) -> tuple[np.ndarray, np.ndarray]:
    """Match a phrase; element i of `tid_groups` is the set of acceptable
    term ids at phrase position i (len>1 for the match_phrase_prefix
    expansion of the trailing term).

    Returns (docs int64[], freqs int64[]) of matching docs. freq = number
    of phrase occurrences (Lucene phraseFreq with slop=0; window count for
    sloppy matches).
    """
    if any(not g for g in tid_groups):
        return np.empty(0, np.int64), np.empty(0, np.int64)
    stride = _stride(pf)
    if slop <= 0:
        s = _enc_union(pf, tid_groups[0], stride)
        for i in range(1, len(tid_groups)):
            if s.size == 0:
                break
            nxt = _enc_union(pf, tid_groups[i], stride)
            # a start p survives iff term i occurs at p+i
            s = s[np.isin(s + i, nxt, assume_unique=False)]
        if s.size == 0:
            return np.empty(0, np.int64), np.empty(0, np.int64)
        docs = s // stride
        uniq, counts = np.unique(docs, return_counts=True)
        return uniq, counts
    return _sloppy_match(pf, tid_groups, slop, stride)


def _sloppy_match(pf: PostingsField, tid_groups: list[list[int]], slop: int,
                  stride: int) -> tuple[np.ndarray, np.ndarray]:
    """Sloppy phrase: per candidate doc, count minimal windows whose width
    (max(p_i - i) - min(p_i - i)) is <= slop, via a pointer sweep over the
    per-term position lists (the SloppyPhraseScorer recurrence, counting
    windows instead of accumulating 1/(1+distance))."""
    n = len(tid_groups)
    encs = [_enc_union(pf, g, stride) for g in tid_groups]
    if any(e.size == 0 for e in encs):
        return np.empty(0, np.int64), np.empty(0, np.int64)
    doc_sets = [np.unique(e // stride) for e in encs]
    cands = doc_sets[0]
    for ds in doc_sets[1:]:
        cands = cands[np.isin(cands, ds)]
    out_docs: list[int] = []
    out_freqs: list[int] = []
    for d in cands:
        # adjusted positions: p - i must coincide within slop
        plists = []
        for i, e in enumerate(encs):
            mask = (e // stride) == d
            plists.append(np.sort(e[mask] % stride) - i)
        ptr = [0] * n
        freq = 0
        while all(ptr[i] < plists[i].size for i in range(n)):
            vals = [plists[i][ptr[i]] for i in range(n)]
            lo, hi = min(vals), max(vals)
            # repeated phrase terms must land on distinct token
            # occurrences (SloppyPhraseScorer's repeat handling): the raw
            # positions vals[i] + i must not collide
            distinct = len({int(vals[i]) + i for i in range(n)}) == n
            if hi - lo <= slop and distinct:
                freq += 1
                # advance the minimum pointer to look for the next window
            ptr[vals.index(lo)] += 1
        if freq:
            out_docs.append(int(d))
            out_freqs.append(freq)
    return (np.asarray(out_docs, dtype=np.int64),
            np.asarray(out_freqs, dtype=np.int64))


def phrase_impacts(pf: PostingsField, docs: np.ndarray, freqs: np.ndarray,
                   idf_sum: float, sim=None,
                   tids: list[int] | None = None) -> np.ndarray:
    """Eager impacts for phrase hits: idf is the sum over the phrase
    terms (Lucene PhraseWeight passes all TermStatistics to the
    similarity), tf is the phrase frequency.

    With a non-BM25 field similarity the phrase scores as a pseudo-term
    through that similarity, taking the rarest clause term's (df, ttf)
    as the pseudo-term statistics — the eager-impact analog of Lucene
    handing the phrase freq to the configured Similarity."""
    if docs.size == 0:
        return np.empty(0, dtype=np.float32)
    tf = freqs.astype(np.float64)
    from ..index.similarity import BM25Similarity, FieldStats
    if sim is None or isinstance(sim, BM25Similarity):
        # ONE f32 op order shared with the fused positional clause
        # kinds (ops/scoring.positional impact formula): k_d comes from
        # the packed k1ln column when the field carries the positional
        # pack with default parameters, recomputed through the same
        # bm25_norms rounding otherwise — this function is the
        # byte-identity oracle the device engines are gated against.
        k1 = sim.k1 if sim is not None else BM25_K1
        b = sim.b if sim is not None else BM25_B
        if (getattr(pf, "k1ln", None) is not None
                and k1 == BM25_K1 and b == BM25_B):
            k1ln = pf.k1ln
        else:
            k1ln = bm25_norms(pf.doc_len, pf.avg_len, k1, b)[1]
        tf32 = freqs.astype(np.float32)
        num = (np.float32(idf_sum) * tf32) * np.float32(k1 + 1.0)
        return num / (tf32 + k1ln[docs])
    tlist = [t for t in (tids or []) if t >= 0]
    if tlist:
        t_min = min(tlist, key=lambda t: pf.df[t])
        df = float(pf.df[t_min])
        s, e = int(pf.indptr[t_min]), int(pf.indptr[t_min + 1])
        ttf = float(pf.tfs[s:e].sum())
    else:
        df = ttf = max(float(docs.size), 1.0)
    st = FieldStats(df=df, ttf=max(ttf, df),
                    doc_count=float(pf.doc_count),
                    avg_len=float(pf.avg_len),
                    total_len=float(pf.doc_len.sum()))
    return sim.impacts(tf, pf.doc_len[docs].astype(np.float64),
                       st).astype(np.float32)


def terms_idf_sum(pf: PostingsField, tid_groups: list[list[int]]) -> float:
    total = 0.0
    for g in tid_groups:
        for t in g:
            if t >= 0:
                total += float(bm25_idf(float(pf.df[t]), pf.doc_count))
    return total


# ---------------------------------------------------------------------------
# Spans (ref: Lucene span package via index/query/Span*QueryParser.java)
# ---------------------------------------------------------------------------


@dataclass
class Spans:
    """Flat span set: (doc, start, end) triplets sorted by (doc, start, end).
    `end` is exclusive, Lucene-style."""

    docs: np.ndarray    # int64 [n]
    starts: np.ndarray  # int64 [n]
    ends: np.ndarray    # int64 [n]

    @staticmethod
    def empty() -> "Spans":
        z = np.empty(0, dtype=np.int64)
        return Spans(z, z.copy(), z.copy())

    @property
    def size(self) -> int:
        return int(self.docs.size)

    def sorted(self) -> "Spans":
        order = np.lexsort((self.ends, self.starts, self.docs))
        return Spans(self.docs[order], self.starts[order], self.ends[order])

    def doc_freqs(self) -> tuple[np.ndarray, np.ndarray]:
        if self.size == 0:
            return np.empty(0, np.int64), np.empty(0, np.int64)
        uniq, counts = np.unique(self.docs, return_counts=True)
        return uniq, counts


def span_term(pf: PostingsField, tid: int) -> Spans:
    if tid < 0 or pf.pos_data is None:
        return Spans.empty()
    stride = _stride(pf)
    enc = pf.enc_positions(tid, stride)
    if enc.size == 0:
        return Spans.empty()
    docs = enc // stride
    starts = enc % stride
    return Spans(docs, starts, starts + 1)


def span_or(children: list[Spans]) -> Spans:
    children = [c for c in children if c.size]
    if not children:
        return Spans.empty()
    docs = np.concatenate([c.docs for c in children])
    starts = np.concatenate([c.starts for c in children])
    ends = np.concatenate([c.ends for c in children])
    trip = np.unique(np.stack([docs, starts, ends], axis=1), axis=0)
    return Spans(trip[:, 0], trip[:, 1], trip[:, 2])


def span_near(children: list[Spans], slop: int, in_order: bool) -> Spans:
    """Combine child spans per doc: a match is one span from each child,
    all within a window of (total span length + slop); ordered variants
    additionally require child i's span to start at/after child i-1's end.
    Ref: Lucene NearSpansOrdered/NearSpansUnordered."""
    if not children:
        return Spans.empty()
    if len(children) == 1:
        return children[0].sorted()
    if any(c.size == 0 for c in children):
        return Spans.empty()
    cands = children[0].docs
    for c in children[1:]:
        cands = cands[np.isin(cands, c.docs)]
    cands = np.unique(cands)
    out_d: list[int] = []
    out_s: list[int] = []
    out_e: list[int] = []
    for d in cands:
        per = []
        for c in children:
            m = c.docs == d
            per.append(list(zip(c.starts[m].tolist(), c.ends[m].tolist())))
        if in_order:
            matches = _near_ordered(per, slop)
        else:
            matches = _near_unordered(per, slop)
        for s, e in matches:
            out_d.append(int(d))
            out_s.append(s)
            out_e.append(e)
    return Spans(np.asarray(out_d, np.int64), np.asarray(out_s, np.int64),
                 np.asarray(out_e, np.int64)).sorted()


def _near_ordered(per: list[list[tuple[int, int]]], slop: int
                  ) -> list[tuple[int, int]]:
    """Ordered near: recursively choose one span per child with
    start_i >= end_{i-1}; width = (last end - first start) minus the sum
    of matched span lengths must be <= slop."""
    out: list[tuple[int, int]] = []

    def rec(i: int, first_start: int, prev_end: int, len_sum: int) -> None:
        if i == len(per):
            gap = (prev_end - first_start) - len_sum
            if gap <= slop:
                out.append((first_start, prev_end))
            return
        for s, e in per[i]:
            if s >= prev_end:
                rec(i + 1, first_start, e, len_sum + (e - s))

    for s, e in per[0]:
        rec(1, s, e, e - s)
    # dedupe (different inner choices can produce the same envelope)
    return sorted(set(out))


def _near_unordered(per: list[list[tuple[int, int]]], slop: int
                    ) -> list[tuple[int, int]]:
    """Linear pointer sweep (Lucene NearSpansUnordered): keep one
    candidate span per child, test the enclosing window, then advance the
    child whose span starts earliest — O(total spans · n) instead of the
    Cartesian product."""
    n = len(per)
    lists = [sorted(p) for p in per]
    ptr = [0] * n
    out: set[tuple[int, int]] = set()
    while all(ptr[i] < len(lists[i]) for i in range(n)):
        chosen = [lists[i][ptr[i]] for i in range(n)]
        lo = min(s for s, _ in chosen)
        hi = max(e for _, e in chosen)
        len_sum = sum(e - s for s, e in chosen)
        if (hi - lo) - len_sum <= slop:
            out.add((lo, hi))
        # advance the child contributing the earliest start
        starts = [lists[i][ptr[i]][0] for i in range(n)]
        ptr[starts.index(min(starts))] += 1
    return sorted(out)


def bm25f_scores(pfs: list[PostingsField], tids: np.ndarray,
                 idf: np.ndarray, weights: np.ndarray, cap: int
                 ) -> np.ndarray:
    """BM25F over [cap] docs — the host oracle (and fallback) of the
    fused `bm25f` clause kind ("Integrating the Probabilistic Models
    BM25/BM25F into Lucene", PAPERS.md): per term, the per-field tfs
    blend into one length-normalized pseudo-frequency, saturated ONCE
    under a shared idf —

      acc_t(d) = sum_f  (w_f * tf_{f,t}(d)) / lnorm_f(d)
      score(d) = sum_t  idf_t * acc_t(d) / (k1 + acc_t(d))

    All f32, field-then-term accumulation order — op-for-op the fused
    engines' bm25f clause, so both paths are byte-identical. BM25F
    here is defined with the default k1/b (per-field similarity
    overrides stay with the per-field query forms).

    tids: int32 [nf, nt] per-(field, term) term ids (-1 = absent);
    idf: f32 [nt] shared idf; weights: f32 [nf] per-field weights.
    Returns the dense f32 [cap] score column (0 = no match).
    """
    nf, nt = tids.shape
    k1_32 = np.float32(BM25_K1)
    lnorms = []
    for pf in pfs:
        if getattr(pf, "lnorm", None) is not None:
            lnorms.append(pf.lnorm)
        else:
            lnorms.append(bm25_norms(pf.doc_len, pf.avg_len)[0])
    total = np.zeros(cap, np.float32)
    for ti in range(nt):
        acc = np.zeros(cap, np.float32)
        for fi in range(nf):
            pf = pfs[fi]
            t = int(tids[fi, ti])
            tfd = np.zeros(cap, np.float32)
            if t >= 0:
                s, e = int(pf.indptr[t]), int(pf.indptr[t + 1])
                tfd[pf.doc_ids[s:e]] = pf.tfs[s:e].astype(np.float32)
            acc = acc + (np.float32(weights[fi]) * tfd) / lnorms[fi]
        total = total + (np.float32(idf[ti]) * acc) / (k1_32 + acc)
    return total


def span_first(child: Spans, end_limit: int) -> Spans:
    if child.size == 0:
        return child
    m = child.ends <= end_limit
    return Spans(child.docs[m], child.starts[m], child.ends[m])


def span_not(include: Spans, exclude: Spans,
             pre: int = 0, post: int = 0) -> Spans:
    """Keep include spans that do not overlap any (pre/post-expanded)
    exclude span in the same doc. Ref: Lucene SpanNotQuery."""
    if include.size == 0 or exclude.size == 0:
        return include
    keep = np.ones(include.size, dtype=bool)
    for i in range(include.size):
        d = include.docs[i]
        s, e = include.starts[i], include.ends[i]
        m = exclude.docs == d
        if not m.any():
            continue
        xs = exclude.starts[m] - pre
        xe = exclude.ends[m] + post
        if np.any((xs < e) & (xe > s)):
            keep[i] = False
    return Spans(include.docs[keep], include.starts[keep], include.ends[keep])
