"""Elastic degraded mesh: dead-device eviction + live repack.

Reference analog: the allocation/rebalance layer
(AllocationService.reroute, cluster/routing/allocation/) — when a node
dies, Elasticsearch does not pay a per-request failover tax forever: the
unassigned copies are REASSIGNED onto the survivors while the remaining
copies keep serving, and the dead node's return triggers re-replication.
This module maps that onto the device mesh, where "node death" is a
permanently dead (replica-row, device) placement and "reassignment" is
a degraded repack of `PackedShards` onto the surviving replica rows.

The lifecycle (one `ElasticMeshSearcher` per served pack):

  1. **detect** — `RowHealth`, wired into the DistributedSearcher
     dispatch AND collect boundaries (where real device errors
     surface), counts CONSECUTIVE failures per physical replica row;
     timeouts and parse errors never count, matching the failover
     retry rules. `mesh.eviction.failure_threshold` (default 3)
     consecutive failures mark the row dead — a transient
     `shard_error` burst under the threshold evicts nothing.
  2. **repack** — a background thread rebuilds the pack onto the
     surviving rows (`parallel/mesh.reduced_mesh`; fresh merged
     segments, so every fingerprint-keyed cache re-keys cleanly). The
     OLD pack and its pinned `_compiled` programs serve every
     in-flight and new search until the swap — the same keep-serving
     lifecycle a background compaction uses. Repack device uploads are
     breaker-accounted (fielddata) with a GC-backstopped hold.
  3. **swap** — an atomic searcher-pointer swap under a tiny lock; the
     retired pack's resident entries are explicitly evicted and its
     pinned mesh programs counted as dropped (search/resident.py),
     then the pack dies with its last in-flight reference.
  4. **re-expand** — while degraded, a probe
     (`mesh.eviction.probe_interval`) checks the dead rows: injected
     death (`device_dead` rules, utils/faults.py) must have been
     cleared AND a trivial device round trip must succeed. A passing
     probe repacks back onto the FULL mesh, restoring replication.

Eviction/re-expansion events are recorded as reroute-style decisions
(`decisions`) and can be surfaced in cluster state via
`cluster/allocation.apply_mesh_row_decision`. Stats under
`nodes_stats()["dispatch"]["eviction"]`
(rows_dead/repacks/swaps/re_expansions/serving_degraded high-water).

This is the general live-repack substrate: the streaming write path's
background compaction (ROADMAP item 1) and mesh-sharded ANN rebuilds
(item 2) reuse the same build-aside/keep-serving/swap machinery.
"""

from __future__ import annotations

import threading
import time

import numpy as np

from ..utils.errors import (CircuitBreakingError, QueryParsingError,
                            SearchParseError, SearchTimeoutError)
from .mesh import reduced_mesh

DEFAULT_FAILURE_THRESHOLD = 3
DEFAULT_PROBE_INTERVAL_MS = 5000.0

_cfg_mx = threading.Lock()
_cfg = {"failure_threshold": DEFAULT_FAILURE_THRESHOLD,
        "probe_interval_ms": DEFAULT_PROBE_INTERVAL_MS}


def configure(failure_threshold: int | None = None,
              probe_interval_ms: float | None = None) -> None:
    """Node startup hook (`mesh.eviction.failure_threshold`,
    `mesh.eviction.probe_interval`). Process-global defaults, last
    configured node wins — the resident-cache convention; searchers
    constructed with explicit arguments are unaffected."""
    with _cfg_mx:
        if failure_threshold is not None:
            _cfg["failure_threshold"] = max(1, int(failure_threshold))
        if probe_interval_ms is not None:
            _cfg["probe_interval_ms"] = max(0.0, float(probe_interval_ms))


def configured(key: str):
    with _cfg_mx:
        return _cfg[key]


def reset_config(if_current: dict | None = None) -> None:
    """Test/node-close hook: restore the built-in defaults — with
    `if_current`, only while the installed config is still the caller's
    (a closing node must not clobber values a later node configured;
    the fault-registry ownership convention)."""
    with _cfg_mx:
        if if_current is not None and if_current != _cfg:
            return
        _cfg["failure_threshold"] = DEFAULT_FAILURE_THRESHOLD
        _cfg["probe_interval_ms"] = DEFAULT_PROBE_INTERVAL_MS


def config_snapshot() -> dict:
    with _cfg_mx:
        return dict(_cfg)


def run_build_aside(name: str, build, swap, on_abort=None) -> bool:
    """The ONE build-aside / keep-serving / atomic-swap discipline,
    shared by the degraded-mesh repack below and the streaming write
    path's compaction (index/engine.Engine._compact_now):

      * `build()` runs with NO serving lock held — the current
        generation keeps serving every in-flight and new search for
        the whole build;
      * `swap(result)` publishes atomically (it takes its own pointer
        lock, re-validates that the world it snapshotted still stands,
        and returns False to abort when it moved on — the caller's
        next trigger retries);
      * a CircuitBreakingError from `build` means there is no headroom
        for the build-aside copy: keep serving the old generation and
        report through `on_abort(exc)` rather than raise — degraded
        but correct beats dead.

    Returns True only when the swap published."""
    try:
        result = build()
    except CircuitBreakingError as e:
        if on_abort is not None:
            on_abort(e)
        return False
    return bool(swap(result))


class RowHealth:
    """Consecutive-failure tracker over PHYSICAL replica rows.

    Failure classes that never retry in the failover path (timeouts,
    parse errors) never count here either — a deadline miss says the
    query was slow, not that the device is dead — and neither do
    breaker trips: the breakers are host-global and row-agnostic, so
    memory pressure must shed load (429), not evict healthy hardware
    and then demand MORE memory for the build-aside repack. The LAST
    live row can never be evicted (an index with zero copies serves
    nothing; the reference likewise never deallocates the last started
    copy), so its failures keep counting but never cross into death."""

    def __init__(self, n_rows: int, threshold: int | None = None,
                 on_dead=None):
        self.n_rows = n_rows
        self.threshold = (threshold if threshold is not None
                          else configured("failure_threshold"))
        self.on_dead = on_dead
        self._mx = threading.Lock()
        self._consecutive: dict[int, int] = {}
        self._dead: set[int] = set()
        self._excluded: set[int] = set()

    def record_failure(self, phys_row: int, exc: Exception) -> None:
        """One failed attempt against a row. Crossing the threshold
        invokes `on_dead(phys_row)` OUTSIDE the lock (it schedules a
        background repack)."""
        if isinstance(exc, (SearchTimeoutError, SearchParseError,
                            QueryParsingError, CircuitBreakingError)):
            return
        newly_dead = False
        with self._mx:
            if phys_row in self._dead or phys_row in self._excluded:
                return
            n = self._consecutive.get(phys_row, 0) + 1
            self._consecutive[phys_row] = n
            if n >= self.threshold \
                    and len(self._dead | self._excluded) + 1 < self.n_rows:
                self._dead.add(phys_row)
                newly_dead = True
        if newly_dead and self.on_dead is not None:
            self.on_dead(phys_row)

    def mark_dead(self, phys_row: int) -> bool:
        """Immediate eviction, bypassing the consecutive counter — the
        caller observed a failure class that is conclusive on its own
        (the multihost exec-broadcast timeout: a peer that accepted the
        SPMD entry and then wedged would hang every collective, so one
        occurrence is enough; zen-fd likewise fails a node on a single
        ping-handler timeout). The last-live-row guard still applies.
        Returns True when the row newly died (on_dead was invoked)."""
        with self._mx:
            if phys_row in self._dead or phys_row in self._excluded \
                    or len(self._dead | self._excluded) + 1 >= self.n_rows:
                return False
            self._dead.add(phys_row)
        if self.on_dead is not None:
            self.on_dead(phys_row)
        return True

    def record_success(self, phys_row: int) -> None:
        with self._mx:
            if phys_row not in self._dead:
                self._consecutive[phys_row] = 0

    def failures(self, phys_row: int) -> int:
        with self._mx:
            return self._consecutive.get(phys_row, 0)

    def dead_rows(self) -> frozenset[int]:
        with self._mx:
            return frozenset(self._dead)

    def mark_alive(self, phys_rows) -> None:
        """Re-expansion: a probe passed — the rows rejoin with a clean
        failure history."""
        with self._mx:
            for r in phys_rows:
                self._dead.discard(r)
                self._consecutive[r] = 0

    def exclude(self, phys_row: int) -> bool:
        """ADMINISTRATIVE removal — graceful decommission (drain), not
        failure: the row leaves the serving set without touching the
        failure counters and WITHOUT invoking on_dead (the drain caller
        drives its own planned repack; firing the crash path here would
        double-count the transition as an eviction). The last-live-row
        guard applies the same as death: you cannot drain the only row
        serving the index. Excluded rows stay out of dead_rows() — the
        decision log keeps drain and crash distinguishable."""
        with self._mx:
            if phys_row in self._excluded:
                return False
            if len(self._dead | self._excluded) + 1 >= self.n_rows:
                return False
            self._excluded.add(phys_row)
            self._consecutive[phys_row] = 0
            return True

    def include(self, phys_row: int) -> bool:
        """Undo an administrative exclude (a drained host re-admitted
        by a join): clean failure history, back in the serving set.
        Returns True when the row was actually excluded (the undrain
        changed state)."""
        with self._mx:
            was = phys_row in self._excluded
            self._excluded.discard(phys_row)
            self._consecutive[phys_row] = 0
            return was

    def excluded_rows(self) -> frozenset[int]:
        with self._mx:
            return frozenset(self._excluded)

    def out_rows(self) -> frozenset[int]:
        """Everything not serving, whatever the reason: dead OR
        drained. The membership view builder keys on this union; the
        decision log and stats key on the split."""
        with self._mx:
            return frozenset(self._dead | self._excluded)


class ElasticMeshSearcher:
    """A DistributedSearcher that survives permanent device death.

    Drop-in for the plain searcher on the read path (`search` /
    `msearch` / `msearch_submit` with the same signatures, so the
    dispatch scheduler pipelines it unchanged); behind the interface it
    owns the eviction -> repack -> swap -> re-expansion lifecycle. The
    searcher/pack POINTER swaps atomically; an in-flight `_PendingMesh`
    holds the searcher it was submitted on, so the old pack serves
    every already-submitted search to completion (keep-serving)."""

    def __init__(self, node, index_name: str, mesh, *,
                 failure_threshold: int | None = None,
                 probe_interval_ms: float | None = None,
                 on_decision=None):
        self.node = node
        self.index_name = index_name
        self.full_mesh = mesh
        self._full_rows = mesh.shape["replica"]
        self.on_decision = on_decision
        self.probe_interval_ms = (
            probe_interval_ms if probe_interval_ms is not None
            else configured("probe_interval_ms"))
        self.health = RowHealth(self._full_rows,
                                threshold=failure_threshold,
                                on_dead=self._on_row_dead)
        # pointer lock: guards ONLY the (packed, searcher, hold) swap
        # and the background-thread bookkeeping — never held across a
        # build, an upload, or a dispatch
        self._swap_mx = threading.Lock()
        # graftlint: ok(lock-discipline): serialization latch — at most
        # one background repack builds at a time BY DESIGN; the build
        # (pack merge + device upload) runs under it for its whole
        # duration, and no search-path code ever takes it
        self._repack_mx = threading.Lock()
        self._threads: list[threading.Thread] = []
        self._last_probe = 0.0
        self.decisions: list[dict] = []
        pack, hold = self._build_pack(mesh)
        from .distributed import DistributedSearcher
        self.packed = pack
        self._pack_hold = hold
        self.searcher = DistributedSearcher(
            pack, health=self.health,
            replica_ids=tuple(range(self._full_rows)))

    # -- read path (DistributedSearcher interface) -------------------------

    def _current(self):
        with self._swap_mx:
            return self.searcher

    @property
    def n_replicas(self) -> int:
        return self._current().n_replicas

    @property
    def replica_ids(self) -> tuple[int, ...]:
        return self._current().replica_ids

    def search(self, body: dict) -> dict:
        return self.msearch([body])[0]

    def msearch(self, bodies: list[dict], with_partials: bool = False,
                deadline: float | None = None) -> list[dict]:
        self.maybe_probe()
        return self._current().msearch(bodies, with_partials,
                                       deadline=deadline)

    def msearch_submit(self, bodies: list[dict],
                       with_partials: bool = False,
                       deadline: float | None = None):
        self.maybe_probe()
        return self._current().msearch_submit(bodies, with_partials,
                                              deadline=deadline)

    def raw_msearch(self, bodies: list[dict]) -> list[dict]:
        self.maybe_probe()
        return self._current().raw_msearch(bodies)

    # -- lifecycle ---------------------------------------------------------

    def _decide(self, action: str, **kw) -> dict:
        """Record one reroute-style decision (the shape
        cluster/allocation.apply_mesh_row_decision consumes)."""
        d = {"decision": action, "index": self.index_name, **kw}
        with self._swap_mx:
            self.decisions.append(d)
        if self.on_decision is not None:
            self.on_decision(d)
        return d

    def _on_row_dead(self, phys_row: int) -> None:
        from ..search.dispatch import eviction_stats
        eviction_stats.rows_dead.inc()
        self._decide("evict_row", row=phys_row,
                     reason=f"{self.health.threshold} consecutive "
                            "failures")
        self._schedule_repack()

    def _schedule_repack(self) -> None:
        t = threading.Thread(target=self._repack_guarded, daemon=True,
                             name=f"mesh-repack-{self.index_name}")
        with self._swap_mx:
            self._threads = [th for th in self._threads
                             if th.is_alive()] + [t]
        t.start()

    def _repack_guarded(self) -> None:
        """Thread entry: a repack crash (device error uploading, OOM
        outside the breaker, a bug) must surface as a decision — never
        a silently dead daemon thread. Recovery is the read path's
        mismatch reschedule (maybe_probe), paced by the probe
        interval."""
        try:
            self._repack()
        except Exception as e:  # noqa: BLE001 — background lifecycle
            self._decide("repack_failed", reason=repr(e))

    def _build_pack(self, mesh):
        """Build-aside: pack the index onto `mesh` (fresh merged
        segments -> fresh fingerprints/seg_ids, so autotune choices,
        resident entries, and pinned mesh programs all key over
        cleanly) and account its device bytes on the fielddata breaker
        — pinned packs are long-lived HBM tenants exactly like uploaded
        columns. The hold's GC backstop releases when the LAST
        reference (possibly an in-flight search on the retired pack)
        drops."""
        import weakref
        import jax
        from ..utils.breaker import breaker_service
        from .distributed import PackedShards
        pack = PackedShards.from_node_index(self.node, self.index_name,
                                            mesh)
        nbytes = sum(
            int(getattr(leaf, "nbytes", 0))
            for leaf in jax.tree_util.tree_leaves((pack.dev, pack.live)))
        hold = breaker_service().breaker("fielddata").hold(nbytes)
        weakref.finalize(pack, hold.release)
        return pack, hold

    def _repack(self) -> None:
        """Background repack loop: rebuild onto whatever the CURRENT
        health state says the mesh should be, swap, and re-check (a row
        may die while a build is in flight). Serialized by the repack
        latch; the swap itself is the only step under the pointer
        lock."""
        from ..search import resident
        from ..search.dispatch import eviction_stats
        from .distributed import DistributedSearcher
        with self._repack_mx:
            while True:
                dead = set(self.health.dead_rows())
                target = tuple(r for r in range(self._full_rows)
                               if r not in dead)
                with self._swap_mx:
                    cur = self.searcher.replica_ids
                if target == cur or not target:
                    return
                eviction_stats.repacks.inc()
                mesh = (self.full_mesh if not dead
                        else reduced_mesh(self.full_mesh, dead))
                retired: dict = {}

                def build(mesh=mesh):
                    pack, hold = self._build_pack(mesh)
                    return (pack, hold,
                            DistributedSearcher(pack, health=self.health,
                                                replica_ids=target))

                def swap(built, target=target):
                    pack, hold, searcher = built
                    with self._swap_mx:
                        retired["pack"] = self.packed
                        retired["searcher"] = self.searcher
                        self.packed = pack
                        self.searcher = searcher
                        self._pack_hold = hold
                    return True

                # no HBM headroom for the build-aside copy aborts: keep
                # serving the old pack (degraded searches still succeed
                # via failover) and let the next trigger retry
                if not run_build_aside(
                        f"mesh-repack-{self.index_name}", build, swap,
                        on_abort=lambda e: self._decide(
                            "repack_aborted", rows=list(target),
                            reason=str(e))):
                    return
                eviction_stats.swaps.inc()
                eviction_stats.serving_degraded.record(len(dead))
                if len(cur) < self._full_rows \
                        and len(target) == self._full_rows:
                    eviction_stats.re_expansions.inc()
                    self._decide("re_expand", rows=list(target))
                else:
                    self._decide("repack_swapped", rows=list(target))
                # the retired pack keeps serving in-flight searches;
                # its fingerprint-keyed residue is reclaimed NOW
                resident.evict_segments(
                    s.seg_id for s in retired["pack"].shards)
                resident.note_mesh_programs_dropped(
                    len(retired["searcher"]._jit_cache))

    # -- re-expansion ------------------------------------------------------

    def maybe_probe(self) -> None:
        """Opportunistic lifecycle tick on the read path, paced to at
        most one action per `mesh.eviction.probe_interval` and always
        off-thread so no search waits on it. Two jobs: (a) while
        degraded, probe the dead rows for re-expansion; (b) reschedule
        a NEEDED repack whose earlier attempt aborted (breaker
        headroom) or crashed — without this, an aborted repack would
        stall the lifecycle forever (health says one shape, the served
        mesh another, and nothing left to trigger the rebuild)."""
        dead = self.health.dead_rows()
        want = tuple(r for r in range(self._full_rows)
                     if r not in dead)
        with self._swap_mx:
            mismatch = bool(want) and self.searcher.replica_ids != want
            busy = any(t.is_alive() for t in self._threads)
        if not dead and not mismatch:
            return
        now = time.monotonic()
        with self._swap_mx:
            if (now - self._last_probe) * 1000.0 < self.probe_interval_ms:
                return
            self._last_probe = now
        if mismatch and not busy:
            self._schedule_repack()
        if not dead:
            return
        t = threading.Thread(target=self.probe_now, daemon=True,
                             name=f"mesh-probe-{self.index_name}")
        with self._swap_mx:
            self._threads = [th for th in self._threads
                             if th.is_alive()] + [t]
        t.start()

    def probe_now(self) -> list[int]:
        """Probe every dead row; rows that pass rejoin via a background
        repack onto the larger mesh. Returns the revived rows."""
        revived = [r for r in sorted(self.health.dead_rows())
                   if self._probe_row(r)]
        if revived:
            self._decide("row_alive", rows=revived,
                         reason="probe passed")
            self.health.mark_alive(revived)
            self._schedule_repack()
        return revived

    def _probe_row(self, phys_row: int) -> bool:
        """Alive = no device_dead rule still pins the row (the
        deterministic injectable) AND a trivial round trip to each of
        the row's devices succeeds (the real-hardware signal)."""
        import jax
        from ..utils import faults
        for s in range(self.full_mesh.shape["shard"]):
            if faults.device_dead_matches("mesh", index=self.index_name,
                                          shard=s, replica=phys_row):
                return False
        try:
            for dev in np.asarray(self.full_mesh.devices)[phys_row]:
                jax.device_put(np.zeros((), np.float32),
                               dev).block_until_ready()
        except Exception:  # noqa: BLE001 — any device error = still dead
            return False
        return True

    # -- teardown / test support -------------------------------------------

    def await_settled(self, timeout: float = 30.0) -> bool:
        """Block until no repack/probe thread is running AND the served
        mesh matches the health state. Test/bench hook — production
        callers never wait on the lifecycle."""
        cutoff = time.monotonic() + timeout
        while time.monotonic() < cutoff:
            with self._swap_mx:
                threads = list(self._threads)
            for t in threads:
                t.join(timeout=max(0.0, cutoff - time.monotonic()))
            dead = self.health.dead_rows()
            want = tuple(r for r in range(self._full_rows)
                         if r not in dead) or None
            with self._swap_mx:
                settled = (want is None
                           or self.searcher.replica_ids == want)
                busy = any(t.is_alive() for t in self._threads)
            if settled and not busy:
                return True
            time.sleep(0.005)
        return False

    def close(self) -> None:
        self.await_settled(timeout=5.0)
        with self._swap_mx:
            hold = self._pack_hold
        if hold is not None:
            hold.release()
