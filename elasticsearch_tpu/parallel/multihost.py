"""Multi-host device-mesh execution: the DCN data plane, pod-hardened.

Reference analog: the reference scales search across machines by RPC
fan-out + coordinator merge (action/search/type/
TransportSearchTypeAction.java:126-148) over its Netty transport with
per-shard results reduced host-side
(search/controller/SearchPhaseController.java:147-282), and survives
machine death as a first-class event: zen fault-detection pings
(discovery/zen/fd/NodesFaultDetection.java) evict a dead node after N
missed pings and the cluster reroutes and keeps serving.

TPU-first redesign (SURVEY §7 step 6): processes join ONE
jax.distributed runtime; their local devices form a single global
("replica", "shard") Mesh; each host packs ITS rows of the global mesh
arrays (jax.make_array_from_callback serves only the rows this host
owns); a search is then ONE SPMD program whose cross-shard top-k/agg
reduce rides XLA collectives — ICI within a host, DCN between hosts —
instead of application-level RPC merging.

Two host layouts map machines onto the mesh:

  * ``layout="shard"``   — hosts partition the SHARD axis (one replica
    row). Capacity scales with machines; a dead host loses its shards,
    so degraded searches report them as structured
    ``_shards.failures`` partials (PR 4's contract at host scope).
  * ``layout="replica"`` — every host holds a full copy and owns one
    REPLICA row. Throughput scales with machines; a dead host only
    loses replication — survivors re-source every shard and results
    stay byte-identical across the evict/repack swap.

The cluster transport remains the CONTROL plane:

  * pack-spec agreement (MESH_SUMMARY_ACTION): hosts exchange shard
    summaries once at join and each derives the identical PackSpec —
    only metadata crosses the control plane, never columns. The stored
    summaries also feed every later membership rebuild, so an eviction
    repack needs NO further agreement round.
  * clock handshake (MESH_CLOCK_ACTION, parallel/clocksync.py): each
    host estimates every peer's monotonic-clock offset from symmetric
    round trips (midpoint estimate, half-RTT uncertainty, min-RTT
    filter). This is what makes the device-side STEPPED deadline
    (PR 8) safe across processes: the driver broadcasts ONE deadline
    on its own clock, every host polls its OWN offset-corrected copy
    inside its io_callback, and the final psum'd verdict stays the
    only collective after the polls. The driver arms stepping only
    when every member's estimate is fresh (conservative pad), so an
    uncertain clock degrades to cooperative timeouts, never to a
    wrong preemption.
  * heartbeat (MESH_PING_ACTION, the zen-fd analog): every host pings
    its peers; ``mesh.ping_retries`` consecutive misses — or a single
    exec-broadcast TIMEOUT (an accepted-then-wedged peer would hang
    every collective) — marks a host dead. Survivors then rebuild a
    reduced host mesh (parallel/mesh.host_mesh) over the surviving
    device rows on the shared build-aside/keep-serving/swap substrate
    (parallel/repack.run_build_aside): the old pack serves every
    in-flight and new search until the atomic swap. A probe
    (``host_dead_matches`` + a real ping) re-admits a repaired host
    and re-expands to the full mesh. Each ping doubles as a clock
    re-sync sample.
  * program entry (MESH_EXEC_ACTION): SPMD requires every process to
    enter the same compiled call in the same order. The broadcast
    carries a per-epoch sequence number plus a FLOOR (the lowest seq
    still outstanding) so an abandoned broadcast can never wedge a
    peer's turn queue, and a membership EPOCH that fences stale turns:
    a rejoined host's undelivered old-epoch messages are rejected with
    StaleEpochError instead of replaying against the new mesh.
    Per-peer sends retry with backoff (ctrl_drop food).
  * fetch (MESH_FETCH_ACTION): hits live on the owning host; a fetch
    that fails (host died between exec and fetch) degrades those hits
    to structured failures instead of raising the whole search.

Pod coordination (parallel/membership.py, the zen2 analog) hardens the
control plane into a coordination service:

  * coordinator LEASE (MESH_LEASE/RELEASE actions): minting exec seqs
    requires holding the lease — won by a majority vote of the
    committed member set, renewed implicitly by every fenced exec,
    handed off on request when idle, and failed over by expiry to a
    highest-acked-epoch survivor. A concurrent driver is fenced with
    LeaseFencedError (409) and retries; this replaces the old "one
    driver at a time by convention" and its residual seq-collision
    window.
  * quorum-fenced membership (``membership="quorum"``, OPT-IN — the
    2-host eviction arc needs the default ``"health"`` threshold
    mode): a transition commits only when a majority of the LAST
    committed member set promises it (MESH_PROPOSE/COMMIT). The
    minority side of a partition refuses its own transition
    (``transition_refused_no_quorum`` decision + the
    partitions_survived counter) and keeps serving its last committed
    epoch degraded until the heal, when the majority's higher
    committed epoch — authoritative even over a CHANGED member set —
    syncs it forward.
  * scoped device-runtime sessions (``session="scoped"``): each host's
    data plane is a mesh over its OWN devices (mesh.local_mesh)
    running its shard span as a purely local program; the driver
    merges member raws host-side (_merge_scoped, the
    SearchPhaseController shape at host scope). No shared
    jax.distributed runtime ties process lifetimes together, which is
    what makes TRUE elastic membership possible: a replacement process
    joins a LIVE pod (MESH_JOIN hello/admit handshake + MESH_PULL doc
    bootstrap) without restarting survivors — replica layouts stay
    byte-identical through kill→replace, shard layouts degrade to
    structured partials and heal.
  * explicit ABANDON (MESH_ABANDON): a driver that aborts a broadcast
    after SOME peers accepted tells them, so gate-waiters release
    immediately instead of riding the exec budget out (closing the
    PR 13 mid-broadcast residual).
  * drain (drain_host): administrative decommission, distinguished
    from a crash in the decision log and the membership counters
    (search/dispatch.MembershipStats → nodes_stats()["dispatch"]
    ["membership"]).

Every boundary above runs the control-plane fault hooks
(utils/faults.py ``host_dead`` / ``ctrl_drop`` / ``ctrl_delay`` /
``net_partition``), so the entire death→evict→repack→rejoin arc — and
the partition→refuse→heal→converge and kill→replace arcs — is
deterministically testable in one process (tests/test_mesh_elastic.py,
tests/test_membership.py).

Hardware note: exercised on a multi-process CPU mesh
(tests/test_multihost.py spawns real OS processes) and, in-process, on
the 8-virtual-device test platform. On TPU pods the same code path
uses the ICI/DCN collectives — the mesh shape is the only difference.
"""

from __future__ import annotations

import json
import math
import threading
import time
from concurrent.futures import TimeoutError as _FUT_TIMEOUT

import numpy as np

from .clocksync import (ClockOffset, ClockSample, ClockTable,
                        correct_deadline)
from .distributed import (PackedShards, PackSpec, DistributedSearcher,
                          summarize_shards, merge_shard_partials,
                          finalize_partials)
from .membership import (CoordinatorLease, NoQuorumError, PodCoordinator,
                         PodLedger, KIND_COMMIT, KIND_LEASE_RELEASE,
                         KIND_LEASE_VOTE, KIND_PROPOSE)
from .mesh import host_mesh, local_mesh
from .repack import RowHealth, run_build_aside
from ..search.controller import shards_header, shard_failure
from ..utils import faults
from ..utils.errors import (HostDownError, LeaseFencedError,
                            SearchTimeoutError, StaleEpochError)
from ..utils.settings import Settings, parse_time_value

MESH_SUMMARY_ACTION = "internal:mesh/summary"
MESH_EXEC_ACTION = "internal:mesh/exec"
MESH_FETCH_ACTION = "internal:mesh/fetch"
MESH_CLOCK_ACTION = "internal:mesh/clock"
MESH_PING_ACTION = "internal:mesh/ping"
MESH_ABANDON_ACTION = "internal:mesh/abandon"
MESH_JOIN_ACTION = "internal:mesh/join"
MESH_PULL_ACTION = "internal:mesh/pull"
MESH_LEASE_ACTION = "internal:mesh/lease_vote"
MESH_RELEASE_ACTION = "internal:mesh/lease_release"
MESH_PROPOSE_ACTION = "internal:mesh/propose"
MESH_COMMIT_ACTION = "internal:mesh/commit"

# PodCoordinator round kind -> control-plane action
_KIND_ACTIONS = {KIND_LEASE_VOTE: MESH_LEASE_ACTION,
                 KIND_LEASE_RELEASE: MESH_RELEASE_ACTION,
                 KIND_PROPOSE: MESH_PROPOSE_ACTION,
                 KIND_COMMIT: MESH_COMMIT_ACTION}


def mesh_timeouts(settings: "Settings | None" = None) -> dict:
    """Control-plane wait budgets in SECONDS, settings-driven so slow
    pods (cold container starts, big packs crossing DCN) can stretch
    them instead of hard-failing packing on the old literals.

    * `mesh.pack_send_timeout`  — one summary send attempt (was 5s)
    * `mesh.pack_sync_timeout`  — all peers' summaries + the peer
      handler-registration retry window (was 60s / 30s)
    * `mesh.exec_timeout`       — SPMD entry turn + remote exec ack +
      pack-ready gate (was 120s)
    * `mesh.fetch_timeout`      — one cross-host fetch (was 30s)
    """
    s = settings or Settings.EMPTY
    ms = {"pack_send": parse_time_value(
              s.get("mesh.pack_send_timeout"), 5_000),
          "pack_sync": parse_time_value(
              s.get("mesh.pack_sync_timeout"), 60_000),
          "exec": parse_time_value(s.get("mesh.exec_timeout"), 120_000),
          "fetch": parse_time_value(s.get("mesh.fetch_timeout"), 30_000)}
    return {k: v / 1000.0 for k, v in ms.items()}


def mesh_fd_config(settings: "Settings | None" = None) -> dict:
    """Failure-detection / clock-sync knobs (zen-fd's
    `discovery.zen.fd.ping_interval|ping_timeout|ping_retries` mapped
    onto the mesh, plus the clock-sync contract):

    * `mesh.ping_interval`     — heartbeat cadence, ms (<=0: no
      background thread; tests drive `heartbeat_now()` explicitly)
    * `mesh.ping_timeout`      — one ping round trip, ms
    * `mesh.ping_retries`      — consecutive misses that evict
    * `mesh.probe_interval`    — dead-host rejoin probe cadence, ms
    * `mesh.clock_samples`     — handshake round trips per peer
    * `mesh.clock_max_uncertainty` — ms; a peer whose offset pad
      exceeds this drops the mesh to cooperative timeouts
    * `mesh.exec_retries`      — per-peer exec-broadcast send retries
    * `mesh.exec_backoff`      — base backoff between retries, ms
    * `mesh.lease_ttl`         — coordinator lease TTL, ms: a dead
      lease holder fails over after this long; a live driver renews
      implicitly with every exec
    """
    s = settings or Settings.EMPTY
    return {
        "ping_interval": parse_time_value(
            s.get("mesh.ping_interval"), 1_000) / 1000.0,
        "ping_timeout": parse_time_value(
            s.get("mesh.ping_timeout"), 2_000) / 1000.0,
        "ping_retries": int(s.get("mesh.ping_retries") or 3),
        "probe_interval": parse_time_value(
            s.get("mesh.probe_interval"), 3_000) / 1000.0,
        "clock_samples": int(s.get("mesh.clock_samples") or 5),
        "clock_max_uncertainty": parse_time_value(
            s.get("mesh.clock_max_uncertainty"), 250) / 1000.0,
        "exec_retries": int(s.get("mesh.exec_retries") or 4),
        "exec_backoff": parse_time_value(
            s.get("mesh.exec_backoff"), 50) / 1000.0,
        "lease_ttl": parse_time_value(
            s.get("mesh.lease_ttl"), 5_000) / 1000.0,
    }


def init_multihost(coordinator_address: str, num_processes: int,
                   process_id: int, platform: str | None = None) -> None:
    """Join the jax.distributed runtime. Idempotent for IDENTICAL
    arguments; re-initialization with a DIFFERENT coordinator or
    topology raises instead of silently returning the stale runtime —
    jax.distributed binds once per process, so the caller would
    otherwise run against a mesh it did not ask for.

    A runtime initialized EARLIER by a direct
    jax.distributed.initialize call (required before any jax
    computation — e.g. before importing this framework) is adopted
    when its coordinator/topology match, and rejected the same way
    when they differ."""
    import jax
    from jax._src import distributed as _jdist
    args = (str(coordinator_address), int(num_processes),
            int(process_id))
    prev = getattr(init_multihost, "_args", None)
    if prev is None and _jdist.global_state.client is not None:
        # bound directly at program start: adopt the live runtime's
        # identity as ours
        prev = (str(_jdist.global_state.coordinator_address
                    or coordinator_address),
                int(jax.process_count()), int(jax.process_index()))
        init_multihost._args = prev  # type: ignore[attr-defined]
    if prev is not None:
        if prev != args:
            raise RuntimeError(
                f"init_multihost already bound this process to "
                f"coordinator={prev[0]} num_processes={prev[1]} "
                f"process_id={prev[2]}; re-initializing with "
                f"{args} requires a process restart (jax.distributed "
                "cannot re-bind)")
        return
    if platform:
        jax.config.update("jax_platforms", platform)
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)
    init_multihost._args = args  # type: ignore[attr-defined]


def _mesh_devices(need: int):
    """The canonical global device order: process-major, id-minor —
    host i's device span sits at its host-order offset. On a REAL
    multi-process runtime the declared topology must consume every
    device exactly (a prefix slice would silently map one host's span
    onto another process's devices, failing later with an obscure
    placer error); in one process (the in-process harness, 8 virtual
    devices) a prefix slice is the intended way to carve a smaller
    mesh."""
    import jax
    devs = sorted(jax.devices(), key=lambda d: (d.process_index, d.id))
    if len(devs) < need:
        raise ValueError(f"multi-host mesh wants {need} devices, "
                         f"have {len(devs)}")
    if len(devs) != need and jax.process_count() > 1:
        raise ValueError(
            f"multi-process mesh wants one device per shard row "
            f"({need} declared, {len(devs)} devices) — a partial "
            "span would cross process ownership")
    return devs[:need]


def global_mesh(n_shards: int):
    """One single-replica-row mesh over the first n_shards devices,
    shard axis process-major (process p's local devices own a
    contiguous shard-row span)."""
    devs = _mesh_devices(n_shards)
    return host_mesh(np.asarray(devs).reshape(1, n_shards))


def _row_placer(mesh, n_shards: int, offset: int, n_local: int):
    """Placer serving only this host's shard rows [offset, offset+n_local)
    of global [n_shards, ...] arrays."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    def place(local: np.ndarray):
        shape = (n_shards,) + local.shape[1:]
        sharding = NamedSharding(
            mesh, P("shard", *([None] * (local.ndim - 1))))

        def cb(index):
            rows = index[0]
            lo = 0 if rows.start is None else rows.start
            hi = shape[0] if rows.stop is None else rows.stop
            if lo < offset or hi > offset + n_local:
                raise RuntimeError(
                    f"device asked for shard rows [{lo}:{hi}) outside "
                    f"this host's span [{offset}:{offset + n_local})")
            return local[(slice(lo - offset, hi - offset),)
                         + tuple(index[1:])]

        return jax.make_array_from_callback(shape, sharding, cb)

    return place


def _param_placer(mesh, n_shards: int, offset: int, n_local: int):
    """Like _row_placer but for query params [S_local, B, ...] with
    P("shard", "replica") — the replica axis is 1 in shard-layout
    meshes, so the batch dim is fully replicated per shard row."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    def place(local):
        local = np.asarray(local)
        shape = (n_shards,) + local.shape[1:]
        sharding = NamedSharding(
            mesh, P("shard", "replica",
                    *([None] * (local.ndim - 2))))

        def cb(index):
            rows = index[0]
            lo = 0 if rows.start is None else rows.start
            hi = shape[0] if rows.stop is None else rows.stop
            if lo < offset or hi > offset + n_local:
                raise RuntimeError(
                    f"device asked for shard rows [{lo}:{hi}) outside "
                    f"this host's span [{offset}:{offset + n_local})")
            return local[(slice(lo - offset, hi - offset),)
                         + tuple(index[1:])]

        return jax.make_array_from_callback(shape, sharding, cb)

    return place


def _full_placer(mesh, with_replica_dim: bool = False):
    """Placer for a host that can serve EVERY row: the replica layout
    (each host holds a full copy; any device's shard-row request
    resolves locally) and the in-process harness (every device is
    local). `with_replica_dim` adds the replica axis to dim 1 — the
    query-param batch split."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    def place(local):
        local = np.asarray(local)
        axes = (("shard", "replica") if with_replica_dim and
                local.ndim >= 2 else ("shard",))
        sharding = NamedSharding(
            mesh, P(*axes, *([None] * (local.ndim - len(axes)))))
        return jax.make_array_from_callback(
            local.shape, sharding, lambda index: local[index])

    return place


def _wire_raw(raw: dict) -> dict:
    """Strip a raw_msearch result down to what the scoped control
    plane ships: candidate arrays, total, agg partials. `agg_specs`
    and `packed` stay host-local — the driver merges with its OWN
    parsed specs (every member parsed the same bodies), and the pack
    handle is a device-memory object with no wire form."""
    import jax
    partials = raw.get("partials")
    if partials is not None:
        partials = jax.tree_util.tree_map(
            lambda x: np.asarray(x) if hasattr(x, "shape") else x,
            partials)
    return {"score": np.asarray(raw["score"]),
            "shard": np.asarray(raw["shard"]),
            "doc": np.asarray(raw["doc"]),
            "total": int(raw["total"]),
            "partials": partials}


def _step_placer(mesh):
    """Placer for the stepped-deadline scalar vector: replicated
    PartitionSpec, but each PROCESS serves its OWN value — the
    offset-corrected deadline is per-host by design (clocksync)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    def place(arr):
        local = np.asarray(arr)
        return jax.make_array_from_callback(
            local.shape, NamedSharding(mesh, P()),
            lambda index: local[index])

    return place


class _MeshView:
    """One membership epoch's immutable serving state: the mesh, the
    pack, the searcher, and the reduced->global shard translation. The
    POINTER to the current view swaps atomically on a membership
    change; in-flight execs hold the view they started on, so a
    retired pack keeps serving them to completion (keep-serving)."""

    __slots__ = ("epoch", "members", "searcher", "packed", "hold",
                 "gmap", "g2r", "dead_sids", "owner_by_sid",
                 "scoped_offs")

    def __init__(self, epoch: int, members: tuple, searcher, packed,
                 hold, gmap: list[int], dead_sids: list[int],
                 owner_by_sid: dict[int, str],
                 scoped_offs: "dict[str, int] | None" = None):
        self.epoch = epoch
        self.members = tuple(members)
        self.searcher = searcher
        self.packed = packed
        self.hold = hold
        self.gmap = list(gmap)              # reduced sid -> global sid
        self.g2r = {g: r for r, g in enumerate(gmap)}
        self.dead_sids = list(dead_sids)    # global sids with no source
        self.owner_by_sid = dict(owner_by_sid)
        # scoped sessions only: each member's span offset in the
        # reduced sid space (the driver translates peer-local shard
        # indices through it; None under a global session)
        self.scoped_offs = (dict(scoped_offs)
                            if scoped_offs is not None else None)


class MultiHostIndex:
    """A mesh index whose rows live on different hosts, elastic under
    machine death.

    All hosts construct this with the SAME global layout. Searches are
    driven from one host at a time via msearch(); the other hosts join
    the SPMD program through the epoch-fenced control-plane exec
    broadcast. See the module docstring for the failure semantics.

    `layout="shard"` (default): hosts own disjoint shard spans
    (`host_shards`: {host_id: n_shards_owned}, iterated in host_order).
    `layout="replica"`: every host passes the SAME full shard list and
    owns one replica row of an (n_hosts, n_shards) mesh.

    `all_shards` (shard layout only) marks a host that can place EVERY
    shard row — required when several logical hosts share one OS
    process (the in-process chaos harness), where the runtime asks each
    host's placer for all rows; harmless otherwise. Eviction semantics
    are unchanged by it: a dead host's shards still degrade to
    failures (the copies are placement-only, not replicas).

    `session="scoped"` decouples the data plane from process
    lifetimes: each host serves its span from a mesh over its OWN
    devices and the driver merges raws host-side — required for
    `join=True` (a replacement process joining a live pod) and for
    `drain_host`-then-rejoin without survivor restarts. The default
    `"global"` keeps the one-SPMD-program path.

    `membership="quorum"` fences every transition on a majority of the
    last committed member set (split-brain safe; needs >= 3 hosts to
    tolerate a loss). The default `"health"` keeps the threshold-
    eviction mode (a 2-host pod can still evict).

    `join=True` (scoped sessions only): this process REPLACES a known
    seat in an already-running pod — instead of the founding summary
    allgather it runs the MESH_JOIN hello/admit handshake against the
    live members and adopts their epoch, lease, and clock estimates.

    `clock` injects the monotonic clock (skew tests); production uses
    time.monotonic.
    """

    def __init__(self, transport, my_id: str, host_order: list[str],
                 local_shards, mapper, host_shards: dict[str, int],
                 settings: "Settings | None" = None,
                 layout: str = "shard",
                 all_shards: "list | None" = None,
                 session: str = "global",
                 membership: str = "health",
                 join: bool = False,
                 clock=None):
        if layout not in ("shard", "replica"):
            raise ValueError(f"unknown mesh layout [{layout}]")
        if session not in ("global", "scoped"):
            raise ValueError(f"unknown mesh session [{session}]")
        if membership not in ("health", "quorum"):
            raise ValueError(f"unknown membership mode [{membership}]")
        if join and session != "scoped":
            raise ValueError(
                'join=True requires session="scoped": a global '
                "jax.distributed runtime binds every process lifetime "
                "to the pod's — only scoped per-host runtimes can "
                "admit a replacement without restarting survivors")
        # wait budgets FIRST: control-plane handlers registered below
        # may fire (from a faster host) before __init__ finishes
        self.timeouts = mesh_timeouts(settings)
        self.fd = mesh_fd_config(settings)
        self._clock = clock if clock is not None else time.monotonic
        self.transport = transport
        self.my_id = my_id
        self.layout = layout
        self.session = session
        self.membership_mode = membership
        self.host_order = list(host_order)
        self.peers = [h for h in host_order if h != my_id]
        self.host_shards = dict(host_shards)
        if layout == "replica":
            self.n_shards = len(local_shards)
            if any(v != self.n_shards for v in host_shards.values()):
                raise ValueError(
                    "replica layout: every host holds the full shard "
                    f"set ({self.n_shards}), got {host_shards}")
            self.offsets = {h: 0 for h in host_order}
            self.my_offset = 0
        else:
            self.n_shards = sum(host_shards.values())
            offsets: dict[str, int] = {}
            off = 0
            for h in host_order:
                offsets[h] = off
                off += host_shards[h]
            self.offsets = offsets
            self.my_offset = offsets[my_id]
            if len(local_shards) != host_shards[my_id]:
                raise ValueError(
                    "local shard count != declared host_shards")
            if all_shards is not None \
                    and len(all_shards) != self.n_shards:
                raise ValueError(
                    f"all_shards must cover every global row "
                    f"({self.n_shards}), got {len(all_shards)}")
        self.local_shards = list(local_shards)
        self.all_shards = (list(local_shards) if layout == "replica"
                           else (list(all_shards)
                                 if all_shards is not None else None))
        self.mapper = mapper

        # -- control plane state ---------------------------------------
        self._summaries: dict[str, dict] = {}
        self._summaries_ready = threading.Event()
        # exec turn: per-epoch FIFO over driver-minted seqs. The
        # condition is RELEASED while a turn's raw_msearch runs, so a
        # blocked waiter wakes to check its deadline instead of
        # sleeping through a peer's whole execution. _exec_epoch
        # mirrors the view's epoch UNDER THE TURN LOCK so waiters
        # never need _swap_mx (lock order: _swap_mx > _exec_turn,
        # one direction only).
        self._exec_turn = threading.Condition()
        self._exec_epoch = 0
        self._exec_next = 0
        self._exec_floor = 0
        self._exec_running = False
        # driver-side seq mint + outstanding floors, per epoch
        self._exec_lock = threading.Lock()
        self._next_seq = 0
        self._outstanding: dict[int, set[int]] = {}
        # seqs a driver explicitly ABANDONED mid-broadcast (guarded by
        # _exec_turn; reset with the turn space on every epoch move)
        self._abandoned: set[int] = set()
        # membership
        self.health = RowHealth(len(host_order),
                                threshold=self.fd["ping_retries"],
                                on_dead=self._on_host_dead)
        self.clock_table = ClockTable(clock=self._clock)
        # pod coordination: the replicated membership ledger, the
        # coordinator lease, and the round orchestrator over both
        # (parallel/membership.py — quorum math and fencing live
        # there; this class only maps rounds onto the control plane)
        self.ledger = PodLedger(0, host_order, host_shards)
        self.lease = CoordinatorLease(my_id, self.fd["lease_ttl"],
                                      clock=self._clock)
        self.coord = PodCoordinator(
            my_id, self.ledger, self.lease,
            submit=self._coord_submit, peers=self._alive_members,
            round_timeout_s=self.timeouts["pack_send"],
            on_peer_error=lambda h, e: self.health.record_failure(
                self._host_idx(h), e))
        # the last membership target a quorum round REFUSED: damps the
        # minority side to one refusal decision per distinct target
        # instead of one per heartbeat (guarded by _rebuild_mx)
        self._refused_target: tuple | None = None
        # pointer lock: guards ONLY the view swap + bookkeeping —
        # never held across a build, an upload, a send, or a dispatch
        self._swap_mx = threading.Lock()
        # graftlint: ok(lock-discipline): serialization latch — at most
        # one background membership rebuild at a time BY DESIGN; the
        # build (pack + device upload) runs under it for its whole
        # duration, and no search-path code ever takes it
        self._rebuild_mx = threading.Lock()
        self._threads: list[threading.Thread] = []
        self._last_probe = 0.0
        self.decisions: list[dict] = []
        self._closed = threading.Event()
        # exec/fetch arrive as soon as a FASTER host finishes its own
        # __init__; they must wait until this host's pack exists
        self._ready = threading.Event()
        transport.register_handler(MESH_SUMMARY_ACTION, self._on_summary)
        transport.register_handler(MESH_EXEC_ACTION, self._on_exec)
        transport.register_handler(MESH_FETCH_ACTION, self._on_fetch)
        transport.register_handler(MESH_CLOCK_ACTION, self._on_clock)
        transport.register_handler(MESH_PING_ACTION, self._on_ping)
        transport.register_handler(MESH_ABANDON_ACTION, self._on_abandon)
        transport.register_handler(MESH_JOIN_ACTION, self._on_join)
        transport.register_handler(MESH_PULL_ACTION, self._on_pull)
        transport.register_handler(MESH_LEASE_ACTION, self._on_lease_vote)
        transport.register_handler(MESH_RELEASE_ACTION,
                                   self._on_lease_release)
        transport.register_handler(MESH_PROPOSE_ACTION, self._on_propose)
        transport.register_handler(MESH_COMMIT_ACTION, self._on_commit)

        mine = summarize_shards(self.local_shards)
        self._accept_summary(my_id, mine)
        if join:
            # -- join a LIVE pod: hello/admit handshake ----------------
            self._join_pod(mine)
        else:
            # -- found: summary allgather -> identical PackSpec --------
            for h in self.peers:
                deadline = time.time() + self.timeouts["pack_sync"]
                while True:  # peers may still be registering handlers
                    try:
                        self._ctrl_send(h, MESH_SUMMARY_ACTION,
                                        {"host": my_id, "summary": mine},
                                        timeout=self.timeouts["pack_send"])
                        break
                    except Exception:
                        if time.time() > deadline:
                            raise
                        time.sleep(0.2)
            if not self._summaries_ready.wait(
                    timeout=self.timeouts["pack_sync"]):
                missing = set(host_order) - set(self._summaries)
                raise TimeoutError(
                    f"pack summaries missing from {missing}")
            if layout == "replica":
                # replicas must be content-identical or the
                # byte-identity contract across an eviction swap is a
                # lie
                for h, s in self._summaries.items():
                    if s != mine:
                        raise ValueError(
                            f"replica layout: [{h}]'s pack summary "
                            "differs from mine — replica hosts must "
                            "index identical content")

            # -- clock handshake (before the first search can carry a
            #    deadline; each later ping refreshes the estimate) -----
            for h in self.peers:
                self._clock_handshake(h)

            # -- data plane: the full-membership view ------------------
            self._view = self._build_view(0, tuple(self.host_order))
            self._ready.set()

        if self.fd["ping_interval"] > 0:
            t = threading.Thread(target=self._heartbeat_loop,
                                 daemon=True,
                                 name=f"mesh-fd-{self.my_id}")
            self._threads.append(t)
            t.start()

    # -- control-plane plumbing (every boundary runs the fault hooks) ----

    def _ctrl_send(self, host: str, action: str, payload: dict,
                   timeout: float) -> dict:
        faults.on_ctrl(action, host=host, me=self.my_id)
        return self.transport.send_request(host, action, payload,
                                           timeout=timeout)

    def _ctrl_submit(self, host: str, action: str, payload: dict,
                     timeout: float):
        faults.on_ctrl(action, host=host, me=self.my_id)
        return self.transport.submit_request(host, action, payload,
                                             timeout=timeout)

    def _coord_submit(self, host: str, kind: str, payload: dict):
        """PodCoordinator's transport: round kind -> mesh action."""
        return self._ctrl_submit(host, _KIND_ACTIONS[kind], payload,
                                 timeout=self.timeouts["pack_send"])

    def _learn_addr(self, host: str, addr) -> None:
        """Fold a peer's advertised transport address in (a replacement
        process may come back on a different port). Transports without
        dynamic peers (LocalHub routes by id) simply lack the hook."""
        add = getattr(self.transport, "add_peer", None)
        if add is not None and addr:
            try:
                add(host, tuple(addr))
            except Exception:  # noqa: BLE001 — advisory only
                pass

    # -- handlers ---------------------------------------------------------

    def _accept_summary(self, host: str, summary: dict) -> None:
        self._summaries[host] = summary
        if set(self._summaries) >= set(self.host_order):
            self._summaries_ready.set()

    def _on_summary(self, src: str, req: dict) -> dict:
        faults.on_ctrl(MESH_SUMMARY_ACTION, host=src, me=self.my_id)
        self._accept_summary(req["host"], req["summary"])
        return {"ok": True}

    def _on_clock(self, src: str, req: dict) -> dict:
        faults.on_ctrl(MESH_CLOCK_ACTION, host=src, me=self.my_id)
        return {"t": self._clock()}

    def _on_ping(self, src: str, req: dict) -> dict:
        faults.on_ctrl(MESH_PING_ACTION, host=src, me=self.my_id)
        with self._swap_mx:
            view = self._view if self._ready.is_set() else None
        return {"t": self._clock(),
                "epoch": view.epoch if view else -1,
                "members": list(view.members) if view else []}

    def _on_exec(self, src: str, req: dict) -> dict:
        faults.on_ctrl(MESH_EXEC_ACTION, host=src, me=self.my_id)
        if not self._ready.wait(timeout=self.timeouts["exec"]):
            raise TimeoutError("mesh host never finished packing")
        epoch = int(req["epoch"])
        members = tuple(req["members"])
        with self._swap_mx:
            view = self._view
            if members == view.members and epoch > view.epoch:
                # catch-up: the peer swapped to the same membership
                # first and numbered it higher (possible when the two
                # sides observed death/rejoin in different orders) —
                # adopt its epoch; no new-epoch turn ran yet
                self._adopt_epoch_locked(epoch)
                view = self._view
        if epoch != view.epoch:
            raise StaleEpochError(
                f"exec for epoch {epoch} {list(members)} arrived at "
                f"epoch {view.epoch} {list(view.members)}",
                epoch=epoch, current=view.epoch)
        if req.get("lease_term") is not None:
            # a turn minted under a stale lease term is a fenced
            # concurrent driver — 409 before any device work
            self.lease.fence(req.get("lease_holder") or "?",
                             int(req["lease_term"]))
        if req.get("scoped"):
            return self._exec_scoped(src, req, view)
        deadline = req.get("deadline")
        stepped = bool(req.get("stepped"))
        local_deadline = self._local_deadline(src, deadline, stepped)
        # SPMD program entry is per-PROCESS: on a multi-process mesh
        # this host MUST enter the driver's program (the collective
        # spans its devices); when one process hosts every mesh device
        # (the in-process harness), the driver's own entry already
        # executes the full program — running it again here would race
        # a SECOND collective execution onto the same device set,
        # which can interleave per-device queues into a deadlock. The
        # handler still plays its TURN either way (ordering + epoch
        # fencing are control-plane contracts, not device work).
        import jax
        self._exec(view, int(req["seq"]), int(req.get("floor", 0)),
                   json.loads(req["bodies"]), local_deadline,
                   stepped if deadline is not None else None,
                   run_program=jax.process_count() > 1)
        return {"ok": True}

    def _exec_scoped(self, src: str, req: dict,
                     view: _MeshView) -> dict:
        """Peer side of a scoped-session exec: run MY span as a local
        program and RETURN the raws in the response. No turn gate —
        there is nothing collective to order (each member's program
        spans only its own devices) — just the epoch and lease fences
        the caller already ran."""
        deadline = req.get("deadline")
        stepped = bool(req.get("stepped"))
        local_deadline = self._local_deadline(src, deadline, stepped)
        raws = view.searcher.raw_msearch(
            json.loads(req["bodies"]), deadline=local_deadline,
            allow_stepped=(stepped if deadline is not None else None))
        return {"ok": True, "raws": [_wire_raw(r) for r in raws]}

    def _on_fetch(self, src: str, req: dict) -> dict:
        faults.on_ctrl(MESH_FETCH_ACTION, host=src, me=self.my_id)
        if not self._ready.wait(timeout=self.timeouts["exec"]):
            raise TimeoutError("mesh host never finished packing")
        with self._swap_mx:
            view = self._view
        epoch = req.get("epoch")
        if epoch is not None and int(epoch) != view.epoch:
            raise StaleEpochError(
                f"fetch for epoch {epoch} at epoch {view.epoch}",
                epoch=int(epoch), current=view.epoch)
        return {"docs": self._fetch_docs(view, req["docs"])}

    def _fetch_docs(self, view: _MeshView, docs) -> list[tuple]:
        """(global shard, row) pairs -> (_id, source) from MY pack —
        the one extraction path the fetch handler AND the driver's
        local-owner branch share."""
        out = []
        for shard, row in docs:
            seg = self._segment_for(view, int(shard))
            out.append((seg.ids[int(row)],
                        seg.sources[int(row)].decode("utf-8",
                                                     "replace")))
        return out

    def _segment_for(self, view: _MeshView, global_sid: int):
        """My pack's segment serving a GLOBAL shard id under `view`."""
        reduced = view.g2r.get(global_sid)
        if reduced is None:
            raise HostDownError(self.my_id, shard=global_sid)
        pk = view.packed
        # scoped sessions pack locally (shard_offset 0) but the reduced
        # space still concatenates member spans — my span's offset in
        # it lives on the view instead of the pack
        base = (view.scoped_offs.get(self.my_id, 0)
                if view.scoped_offs is not None else pk.shard_offset)
        local = reduced - base
        if not 0 <= local < len(pk.shards):
            raise ValueError(
                f"shard {global_sid} (reduced {reduced}) outside this "
                f"host's packed span")
        return pk.shards[local]

    # -- pod coordination handlers ----------------------------------------

    def _current_epoch(self) -> int:
        if not self._ready.is_set():
            return 0
        with self._swap_mx:
            return self._view.epoch

    def _on_abandon(self, src: str, req: dict) -> dict:
        faults.on_ctrl(MESH_ABANDON_ACTION, host=src, me=self.my_id)
        with self._exec_turn:
            if int(req["epoch"]) == self._exec_epoch:
                self._abandoned.add(int(req["seq"]))
                self._exec_turn.notify_all()
        return {"ok": True}

    def _on_lease_vote(self, src: str, req: dict) -> dict:
        faults.on_ctrl(MESH_LEASE_ACTION, host=src, me=self.my_id)
        granted, info = self.lease.vote(
            req["candidate"], int(req["term"]), int(req["epoch"]),
            self._current_epoch(),
            handoff_from=req.get("handoff_from"))
        return {"granted": granted, "lease": info}

    def _on_lease_release(self, src: str, req: dict) -> dict:
        faults.on_ctrl(MESH_RELEASE_ACTION, host=src, me=self.my_id)
        from ..search.dispatch import membership_stats
        holder, _term = self.lease.holder()
        if holder != self.my_id:
            # phantom holder (I crashed-and-replaced, or already let
            # it lapse): nothing to defend — the election decides
            return {"granted": True}
        with self._exec_lock:
            busy = bool(self._outstanding)
        if busy:
            return {"granted": False}
        self.lease.release()
        membership_stats.lease_handoffs.inc()
        self._decide("lease_handoff", to=req.get("candidate"),
                     reason="holder idle; release granted")
        return {"granted": True}

    def _on_propose(self, src: str, req: dict) -> dict:
        faults.on_ctrl(MESH_PROPOSE_ACTION, host=src, me=self.my_id)
        granted, cur = self.ledger.promise(int(req["epoch"]),
                                           req["proposer"])
        return {"promised": granted, "epoch": cur}

    def _on_commit(self, src: str, req: dict) -> dict:
        faults.on_ctrl(MESH_COMMIT_ACTION, host=src, me=self.my_id)
        self._fold_commit(int(req["epoch"]), tuple(req["members"]),
                          host_shards=req.get("host_shards"),
                          summaries=req.get("summaries"),
                          addr=req.get("addr"),
                          proposer=req.get("proposer"),
                          reason=req.get("reason"),
                          drained=req.get("drained"))
        return {"ok": True,
                "epoch": self.ledger.committed().epoch}

    def _fold_commit(self, epoch: int, members: tuple,
                     host_shards=None, summaries=None, addr=None,
                     proposer=None, reason=None,
                     drained=None) -> bool:
        """Adopt a COMMITTED membership record observed on the wire
        (commit fan-out, or epoch catch-up in quorum mode). A committed
        higher epoch is authoritative even over a CHANGED member set —
        the quorum already decided — so unlike the health-mode
        same-members-only adoption this re-admits hosts the local
        health state had written off (the healed-minority arc)."""
        for h, s in (summaries or {}).items():
            self._accept_summary(h, s)
        for h, a in (addr or {}).items():
            self._learn_addr(h, a)
        if not self.ledger.commit(epoch, members, host_shards):
            return False
        if drained is not None:
            # drain is POD state, declared on every quorum commit: a
            # drained seat stays alive on the wire but out of the
            # member set, so without this every OTHER member would
            # ping it reachable and re-propose it straight back in
            # (and the drained host must learn to hold itself out too)
            want = {h for h in drained if h in self.host_order}
            for i in sorted(self.health.excluded_rows()):
                if self.host_order[i] not in want:
                    self.health.include(i)
            for h in want:
                self.health.exclude(self._host_idx(h))
        # the committed set is the liveness ground truth now: clear
        # drain/death state for every member it re-admits (a genuinely
        # dead one just re-fails detection)
        revive = []
        for h in members:
            if h == self.my_id or h not in self.host_order:
                continue
            idx = self._host_idx(h)
            if idx in self.health.out_rows():
                self.health.include(idx)
                revive.append(idx)
        if revive:
            self.health.mark_alive(revive)
            for idx in revive:
                self._clock_handshake(self.host_order[idx])
        self._decide("membership_committed", epoch=epoch,
                     members=list(members), proposer=proposer,
                     reason=reason)
        with self._rebuild_mx:
            self._refused_target = None
        self._schedule_rebuild()
        return True

    # -- pod join (hello / admit / pull) ----------------------------------

    def _on_join(self, src: str, req: dict) -> dict:
        faults.on_ctrl(MESH_JOIN_ACTION, host=src, me=self.my_id)
        if not self._ready.wait(timeout=self.timeouts["exec"]):
            raise TimeoutError("mesh host never finished packing")
        if self.session != "scoped":
            raise ValueError(
                'pod join requires session="scoped" — a global '
                "jax.distributed runtime cannot admit a process "
                "without a full restart")
        host = req["host"]
        if host not in self.host_order:
            raise ValueError(
                f"unknown pod seat [{host}]: a joiner replaces a "
                f"known seat of {self.host_order}")
        with self._swap_mx:
            view = self._view
        if req.get("stage", "hello") == "hello":
            holder, term = self.lease.holder()
            clock = {}
            now = self._clock()
            for h in view.members:
                off = self.clock_table.get(h)
                if off is None or h == host:
                    continue
                # re-stamp on the wire: measured_at lives on MY clock
                # (meaningless to the joiner), so fold the accrued
                # drift into the uncertainty and send age 0 — the
                # joiner composes and stamps with its own now
                clock[h] = {"offset": off.offset,
                            "uncertainty": off.pad(now)}
            return {"epoch": view.epoch, "members": list(view.members),
                    "layout": self.layout,
                    "host_shards": dict(self.host_shards),
                    "summaries": dict(self._summaries),
                    "lease": {"holder": holder, "term": term},
                    "clock": clock}
        # stage == "admit": I drive the transition that seats the
        # joiner (quorum: promise round against the last committed
        # set — a minority-side seed CANNOT admit; health: unilateral
        # commit broadcast)
        from ..search.dispatch import membership_stats
        idx = self._host_idx(host)
        was_out = idx in self.health.out_rows()
        summary = req["summary"]
        if self.layout == "replica" \
                and summary != self._summaries[self.my_id]:
            raise ValueError(
                f"replica joiner [{host}]'s pack summary differs from "
                "the pod's — a replacement must index identical "
                "content (MESH_PULL bootstraps it)")
        self._accept_summary(host, summary)
        addr = req.get("addr")
        if addr:
            self._learn_addr(host, addr)
        # seat the row so the health target includes the joiner
        self.health.include(idx)
        self.health.mark_alive([idx])
        self.clock_table.forget(host)  # fresh process, fresh epoch
        self._clock_handshake(host)
        members = tuple(h for h in self.host_order
                        if h in set(self._alive_members()) | {host})
        extra = {"summaries": {host: summary}}
        if addr:
            extra["addr"] = {host: list(addr)}
        if self.membership_mode == "quorum":
            epoch = self.coord.propose_transition(
                members, dict(self.host_shards),
                reason="replacement" if was_out else "join",
                extra=extra)
        else:
            epoch = max(self.ledger.committed().epoch, view.epoch) + 1
            self.ledger.commit(epoch, members, dict(self.host_shards))
            payload = {"epoch": epoch, "members": list(members),
                       "host_shards": dict(self.host_shards),
                       "proposer": self.my_id,
                       "reason": "replacement" if was_out else "join",
                       **extra}
            for h in members:
                if h in (self.my_id, host):
                    continue
                try:
                    self._ctrl_send(h, MESH_COMMIT_ACTION, payload,
                                    timeout=self.timeouts["pack_send"])
                except Exception:  # noqa: BLE001 — catch-up converges
                    pass
        if was_out:
            membership_stats.replacements.inc()
        else:
            membership_stats.joins.inc()
        self._decide("host_replaced" if was_out else "host_joined",
                     host=host, epoch=epoch)
        self._schedule_rebuild()
        return {"ok": True, "epoch": epoch, "members": list(members),
                "replacement": was_out}

    def _on_pull(self, src: str, req: dict) -> dict:
        """Serve one page of a shard's docs to a bootstrapping joiner
        (replica layout: survivors hold every shard live)."""
        faults.on_ctrl(MESH_PULL_ACTION, host=src, me=self.my_id)
        if not self._ready.wait(timeout=self.timeouts["exec"]):
            raise TimeoutError("mesh host never finished packing")
        with self._swap_mx:
            view = self._view
        seg = self._segment_for(view, int(req["shard"]))
        start = max(0, int(req.get("start", 0)))
        limit = max(1, int(req.get("limit", 500)))
        n = len(seg.ids)
        stop = min(n, start + limit)
        return {"ids": [str(seg.ids[i]) for i in range(start, stop)],
                "sources": [seg.sources[i].decode("utf-8", "replace")
                            for i in range(start, stop)],
                "total": n}

    def _join_pod(self, mine: dict) -> None:
        """Joiner side of the handshake: hello (adopt pod state) ->
        clock seed + direct handshakes -> admit (the seed drives the
        membership transition) -> build my view at the committed
        epoch. Survivors never restart; my device runtime is scoped to
        me."""
        hello = seed = None
        deadline = time.time() + self.timeouts["pack_sync"]
        while hello is None:
            for h in self.peers:
                try:
                    hello = self._ctrl_send(
                        h, MESH_JOIN_ACTION,
                        {"host": self.my_id, "stage": "hello"},
                        timeout=self.timeouts["pack_send"])
                    seed = h
                    break
                except Exception:  # noqa: BLE001 — try the next seat
                    continue
            if hello is None:
                if time.time() > deadline:
                    raise TimeoutError(
                        f"no pod member answered the join hello "
                        f"(asked {self.peers})")
                time.sleep(0.2)
        for h, s in (hello.get("summaries") or {}).items():
            if h != self.my_id:
                self._accept_summary(h, s)
        if self.layout == "replica":
            for h in hello["members"]:
                s = self._summaries.get(h)
                if s is not None and s != mine:
                    raise ValueError(
                        f"replica layout: [{h}]'s pack summary "
                        "differs from mine — pull the pod's docs "
                        "(pull_pod_docs) and re-index before joining")
        # seats the pod runs WITHOUT are dead to me too — quietly: the
        # pod already logged those decisions, re-deciding them here
        # would double-count (on_dead is re-armed after)
        alive = set(hello["members"]) | {self.my_id}
        on_dead, self.health.on_dead = self.health.on_dead, None
        for h in self.host_order:
            if h not in alive:
                self.health.mark_dead(self._host_idx(h))
        self.health.on_dead = on_dead
        # clock: handshake the seed, seed the rest transitively
        # (ClockOffset.compose), then tighten each with a direct
        # handshake — record/seed keep whichever estimate is tighter
        self._clock_handshake(seed)
        to_seed = self.clock_table.get(seed)
        if to_seed is not None:
            now = self._clock()
            for h, e in (hello.get("clock") or {}).items():
                if h == self.my_id:
                    continue
                leg = ClockOffset(float(e["offset"]),
                                  float(e["uncertainty"]), now)
                self.clock_table.seed(h, to_seed.compose(leg))
        for h in sorted(alive - {self.my_id, seed}):
            self._clock_handshake(h)
        lz = hello.get("lease") or {}
        if lz.get("holder"):
            self.lease.adopt(lz["holder"], int(lz.get("term") or 0))
        addr = getattr(self.transport, "advertise_addr", None)
        resp = self._ctrl_send(
            seed, MESH_JOIN_ACTION,
            {"host": self.my_id, "stage": "admit", "summary": mine,
             "addr": list(addr) if addr else None},
            timeout=self.timeouts["pack_sync"])
        epoch = int(resp["epoch"])
        members = tuple(resp["members"])
        self.ledger.commit(epoch, members, dict(self.host_shards))
        self._view = self._build_view(epoch, members)
        self._ready.set()

    @staticmethod
    def pull_pod_docs(transport, my_id: str, seed_hosts,
                      timeout_s: float = 30.0,
                      batch: int = 500) -> tuple[dict, dict]:
        """Pre-join bootstrap for a REPLICA-layout replacement that
        lost its disk: stream every shard's (_id, _source) pairs from
        the first live member so the caller can re-index locally —
        byte-identical pack — and then construct MultiHostIndex with
        join=True. Static: runs before any instance exists. Returns
        (hello state, {global sid: [(id, source), ...]})."""
        hello = seed = None
        for h in seed_hosts:
            try:
                faults.on_ctrl(MESH_JOIN_ACTION, host=h, me=my_id)
                hello = transport.send_request(
                    h, MESH_JOIN_ACTION,
                    {"host": my_id, "stage": "hello"},
                    timeout=timeout_s)
                seed = h
                break
            except Exception:  # noqa: BLE001 — try the next seat
                continue
        if hello is None:
            raise TimeoutError(
                f"no pod member answered the pull hello "
                f"(asked {list(seed_hosts)})")
        if hello.get("layout") != "replica":
            raise ValueError(
                "pull bootstrap is replica-layout only: shard-layout "
                "seats bring their own segments (survivors do not "
                "hold a dead seat's shards)")
        n = int(hello["host_shards"][seed])
        docs: dict[int, list] = {}
        for sid in range(n):
            out: list = []
            start = 0
            while True:
                faults.on_ctrl(MESH_PULL_ACTION, host=seed, me=my_id)
                r = transport.send_request(
                    seed, MESH_PULL_ACTION,
                    {"shard": sid, "start": start, "limit": batch},
                    timeout=timeout_s)
                ids = list(r["ids"])
                out.extend(zip(ids, list(r["sources"])))
                start += len(ids)
                if not ids or start >= int(r["total"]):
                    break
            docs[sid] = out
        return hello, docs

    # -- clock sync -------------------------------------------------------

    def _clock_handshake(self, host: str) -> None:
        """N round trips; the table keeps the min-RTT estimate. A host
        that cannot be sampled simply has no offset — the driver will
        not arm stepping until a later ping samples it."""
        for _ in range(max(1, self.fd["clock_samples"])):
            try:
                t0 = self._clock()
                resp = self._ctrl_send(host, MESH_CLOCK_ACTION, {},
                                       timeout=self.fd["ping_timeout"])
                t1 = self._clock()
            except Exception:
                return
            self.clock_table.record(
                host, ClockSample(t0, float(resp["t"]), t1))

    def _local_deadline(self, driver: str, deadline,
                        stepped: bool) -> float | None:
        """Map the driver-clock deadline onto MY clock, conservatively
        padded (never earlier than the true cutoff). Without an offset
        estimate for the driver: abstain — +inf under a stepped program
        (the driver's own poll still preempts the whole mesh through
        the psum'd verdict; entering the stepped form is what matters,
        a wrong local cutoff would 504 healthy searches), None under a
        cooperative one (the driver enforces its own deadline)."""
        if deadline is None:
            return None
        if driver == self.my_id:
            return float(deadline)
        off = self.clock_table.get(driver)
        if off is not None:
            return correct_deadline(float(deadline), off,
                                    now=self._clock())
        return math.inf if stepped else None

    # -- heartbeat / membership -------------------------------------------

    def _host_idx(self, host: str) -> int:
        return self.host_order.index(host)

    def _decide(self, action: str, **kw) -> dict:
        d = {"decision": action, "host_id": self.my_id, **kw}
        with self._swap_mx:
            self.decisions.append(d)
        return d

    def _alive_members(self) -> tuple:
        # dead OR drained rows leave the serving target; the decision
        # log and the membership counters keep the split observable
        out = self.health.out_rows()
        return tuple(h for i, h in enumerate(self.host_order)
                     if i not in out)

    def drain_host(self, host: str) -> bool:
        """Graceful decommission: administratively remove `host` from
        the serving target WITHOUT counting a failure — an operator
        action is not an incident, and the decision log + the
        membership `drains` counter keep it distinguishable from a
        crash. The seat rejoins via undrain_host (same process) or the
        join handshake (a replacement). Refused (False) for the last
        live row — a pod serving nothing."""
        from ..search.dispatch import membership_stats
        idx = self._host_idx(host)
        if not self.health.exclude(idx):
            return False
        membership_stats.drains.inc()
        self._decide("drain_host", host=host,
                     reason="administrative decommission "
                            "(operator action, not a failure)")
        self._schedule_rebuild()
        return True

    def undrain_host(self, host: str) -> bool:
        """Revert a drain: the seat re-enters the serving target on
        the next rebuild (its process never went away)."""
        idx = self._host_idx(host)
        if not self.health.include(idx):
            return False
        self._decide("undrain_host", host=host, reason="drain reverted")
        self._schedule_rebuild()
        return True

    def _on_host_dead(self, idx: int) -> None:
        host = self.host_order[idx]
        self._decide("evict_host", host=host,
                     reason=f"{self.health.threshold} consecutive "
                            "missed heartbeats or exec timeout")
        # a rejoining process may have restarted: its monotonic epoch
        # is fresh, so the old offset estimate is poison
        self.clock_table.forget(host)
        self._schedule_rebuild()

    def heartbeat_now(self) -> None:
        """One failure-detection round: ping every live peer (each
        response doubles as a clock re-sync sample), and reschedule a
        rebuild whose earlier attempt crashed or aborted (without
        this, an aborted rebuild would stall the lifecycle forever)."""
        dead = self.health.dead_rows()
        for h in self.peers:
            if self._host_idx(h) in dead:
                continue
            self._ping(h, count_failure=True)
        want = self._alive_members()
        with self._swap_mx:
            mismatch = self._view.members != want
            busy = any(t.is_alive() for t in self._threads
                       if t.name.startswith("mesh-rebuild"))
        if mismatch and not busy:
            self._schedule_rebuild()

    def probe_now(self) -> list[str]:
        """Probe every dead host for rejoin: the injected-death rule
        must be gone (faults.host_dead_matches — removing it is how a
        repaired machine comes back) AND a real ping round trip must
        succeed. Revived hosts rejoin via a background rebuild onto
        the larger mesh. Returns the revived hosts."""
        revived = []
        for i in sorted(self.health.dead_rows()):
            host = self.host_order[i]
            if faults.host_dead_matches(host) \
                    or faults.net_partition_matches(self.my_id, host):
                # probes never consume a rule: a severed link is
                # checked, not pinged-through (the ping would just
                # burn a round trip on an injected refusal)
                continue
            if self._ping(host, count_failure=False):
                revived.append(host)
        if revived:
            self._decide("host_rejoin", hosts=revived,
                         reason="probe passed")
            self.health.mark_alive([self._host_idx(h)
                                    for h in revived])
            for h in revived:
                self._clock_handshake(h)
            self._schedule_rebuild()
        return revived

    def _ping(self, host: str, count_failure: bool) -> bool:
        try:
            t0 = self._clock()
            resp = self._ctrl_send(host, MESH_PING_ACTION,
                                   {"host": self.my_id},
                                   timeout=self.fd["ping_timeout"])
            t1 = self._clock()
        except Exception as e:  # noqa: BLE001 — any miss counts
            if count_failure:
                self.health.record_failure(self._host_idx(host), e)
            return False
        self.clock_table.record(
            host, ClockSample(t0, float(resp["t"]), t1))
        self.health.record_success(self._host_idx(host))
        return True

    def _heartbeat_loop(self) -> None:
        interval = self.fd["ping_interval"]
        probe_at = 0.0
        while not self._closed.wait(timeout=interval):
            try:
                self.heartbeat_now()
                now = time.monotonic()
                if self.health.dead_rows() \
                        and now >= probe_at:
                    probe_at = now + self.fd["probe_interval"]
                    self.probe_now()
            except Exception:  # noqa: BLE001 — FD must never die
                pass

    # -- membership rebuild (build-aside / keep-serving / swap) -----------

    def _schedule_rebuild(self) -> None:
        t = threading.Thread(target=self._rebuild_guarded, daemon=True,
                             name=f"mesh-rebuild-{self.my_id}")
        with self._swap_mx:
            self._threads = [th for th in self._threads
                             if th.is_alive()] + [t]
        t.start()

    def _rebuild_guarded(self) -> None:
        try:
            self._rebuild()
        except Exception as e:  # noqa: BLE001 — background lifecycle
            self._decide("rebuild_failed", reason=repr(e))

    def _rebuild(self) -> None:
        """Rebuild the serving view onto whatever the CURRENT health
        state says the membership is, swap, re-check (a host may die
        while a build is in flight). The stored join summaries mean a
        rebuild needs NO new agreement round — every member derives
        the identical reduced spec locally.

        membership="quorum" routes the transition through the pod
        coordinator first: the view only ever converges onto a
        COMMITTED record, and a proposal the electorate refuses
        (NoQuorumError — the minority side of a partition) leaves the
        old epoch serving degraded instead of forking the pod."""
        from ..search.dispatch import eviction_stats
        if not self._ready.wait(timeout=self.timeouts["exec"]):
            return  # a commit raced construction; init builds the view
        with self._rebuild_mx:
            while True:
                # my own index never records failures (hosts monitor
                # their PEERS), so I am always in the target — a full
                # partition converges on every side serving solo
                # (health mode) or on the majority side alone (quorum)
                target = self._alive_members()
                with self._swap_mx:
                    cur_view = self._view
                if self.membership_mode == "quorum":
                    target = self._quorum_target(target)
                    if target is None:
                        return
                    new_epoch = self.ledger.committed().epoch
                    if (target == cur_view.members
                            and new_epoch == cur_view.epoch) \
                            or not target:
                        return
                else:
                    if target == cur_view.members or not target:
                        return
                    new_epoch = cur_view.epoch + 1
                    # mirror into the ledger: the lease electorate is
                    # always the committed member set, so eviction must
                    # shrink it even in health mode
                    self.ledger.commit(new_epoch, target,
                                       dict(self.host_shards))
                eviction_stats.repacks.inc()
                retired: dict = {}

                def build(epoch=new_epoch, members=target):
                    return self._build_view(epoch, members)

                def swap(view):
                    with self._swap_mx:
                        retired["view"] = self._view
                        self._view = view
                        self._reset_turns_locked()
                    return True

                if not run_build_aside(
                        f"mesh-membership-{self.my_id}", build, swap,
                        on_abort=lambda e: self._decide(
                            "rebuild_aborted", members=list(target),
                            reason=str(e))):
                    return
                eviction_stats.swaps.inc()
                eviction_stats.serving_degraded.record(
                    len(self.host_order) - len(target))
                if len(target) == len(self.host_order) \
                        and len(retired["view"].members) \
                        < len(self.host_order):
                    eviction_stats.re_expansions.inc()
                    self._decide("re_expand", members=list(target),
                                 epoch=new_epoch)
                else:
                    self._decide("membership_swapped",
                                 members=list(target), epoch=new_epoch)
                # the retired view keeps serving in-flight execs; its
                # breaker hold releases when the last reference drops
                # (weakref backstop on the pack)

    def _quorum_target(self, target: tuple) -> "tuple | None":
        """Caller holds _rebuild_mx. Converge `target` (the health
        view) with the LEDGER: propose a transition when health moved
        off the committed record, return the committed member order to
        build toward, or None when the electorate refused (stay on the
        old epoch, serving degraded)."""
        from ..search.dispatch import membership_stats
        committed = self.ledger.committed()
        if set(target) != set(committed.members):
            # re-ADDING a seat the committed record dropped needs live
            # proof — a member that merely hasn't noticed a death yet
            # must not propose resurrecting it. That includes MYSELF:
            # a seat the quorum removed (drain, partition eviction)
            # never proposes its own re-admission — a majority member
            # re-adds it once it probes reachable (the master-rejoin
            # rule), or a drain ends with an explicit undrain
            adds = set(target) - set(committed.members)
            confirmed = tuple(
                h for h in target
                if h in committed.members
                or (h in adds and h != self.my_id
                    and self._ping(h, count_failure=False)))
            if set(confirmed) != set(committed.members):
                if self._refused_target == confirmed:
                    return None  # already refused; damp the retry storm
                drained = sorted(self.host_order[i] for i in
                                 self.health.excluded_rows())
                drops = set(committed.members) - set(confirmed)
                reason = ("drain" if drops and drops <= set(drained)
                          else "membership change")
                try:
                    self.coord.propose_transition(
                        confirmed, dict(self.host_shards),
                        reason=reason,
                        extra={"drained": drained})
                except NoQuorumError as e:
                    # a racing proposer may have won this epoch: give
                    # its commit fan-out a beat before calling it a
                    # partition
                    time.sleep(min(0.2, self.coord.round_timeout_s))
                    if self.ledger.committed().epoch > committed.epoch:
                        return self._quorum_target(target)
                    membership_stats.partitions_survived.inc()
                    self._refused_target = confirmed
                    self._decide(
                        "transition_refused_no_quorum",
                        members=list(confirmed), acks=e.acks,
                        needed=e.needed,
                        reason="minority side must not fork the pod; "
                               "serving last committed epoch degraded")
                    return None
                self._refused_target = None
        committed = self.ledger.committed()
        return tuple(h for h in self.host_order
                     if h in committed.members)

    def _adopt_epoch_locked(self, epoch: int) -> None:
        """Caller holds _swap_mx. Same members, higher peer epoch —
        renumber without rebuilding."""
        v = self._view
        self._view = _MeshView(epoch, v.members, v.searcher, v.packed,
                               v.hold, v.gmap, v.dead_sids,
                               v.owner_by_sid,
                               scoped_offs=v.scoped_offs)
        self._reset_turns_locked()

    def _reset_turns_locked(self) -> None:
        """Caller holds _swap_mx (having just installed the new view).
        New epoch: fresh turn space; stale waiters wake, see the epoch
        moved, and raise StaleEpochError to their drivers (seq
        fencing)."""
        epoch = self._view.epoch
        with self._exec_turn:
            self._exec_epoch = epoch
            self._exec_next = 0
            self._exec_floor = 0
            self._abandoned.clear()
            self._exec_turn.notify_all()
        with self._exec_lock:
            self._next_seq = 0

    def _build_view(self, epoch: int, members: tuple) -> _MeshView:
        """Pack + searcher for one membership. The device rows come
        from the canonical process-major order, so every member builds
        the IDENTICAL mesh without coordination."""
        import weakref
        import jax
        from ..utils.breaker import breaker_service

        if self.session == "scoped":
            return self._build_scoped_view(epoch, members)
        if self.layout == "replica":
            S = self.n_shards
            devs = _mesh_devices(len(self.host_order) * S)
            rows = [devs[self._host_idx(h) * S:
                         (self._host_idx(h) + 1) * S]
                    for h in members]
            mesh = host_mesh(rows)
            spec = PackSpec([self._summaries[self.my_id]], S)
            placer = _full_placer(mesh)
            packed = PackedShards("mh", self.local_shards, self.mapper,
                                  mesh, spec=spec, shard_offset=0,
                                  placer=placer)
            packed.place_params = _make_tree_placer(
                _full_placer(mesh, with_replica_dim=True))
            packed.place_aggs = _make_tree_placer(placer)
            gmap = list(range(S))
            dead_sids: list[int] = []
            owner = {s: self.my_id for s in gmap}
            searcher = DistributedSearcher(
                packed,
                replica_ids=tuple(self._host_idx(h) for h in members),
                gather_out=True)
        else:
            devs = _mesh_devices(self.n_shards)
            gmap = []
            spans: dict[str, tuple[int, int]] = {}
            row_devs = []
            owner = {}
            for h in [x for x in self.host_order if x in members]:
                off, n = self.offsets[h], self.host_shards[h]
                spans[h] = (len(gmap), n)
                for s in range(off, off + n):
                    gmap.append(s)
                    owner[s] = h
                row_devs.extend(devs[off: off + n])
            dead_sids = [s for s in range(self.n_shards)
                         if s not in owner]
            mesh = host_mesh(np.asarray(row_devs).reshape(
                1, len(gmap)))
            spec = PackSpec(
                [self._summaries[h] for h in self.host_order
                 if h in members], len(gmap))
            my_red_off, my_n = spans[self.my_id]
            if self.all_shards is not None:
                segs = [self.all_shards[g] for g in gmap]
                placer = _full_placer(mesh)
                packed = PackedShards("mh", segs, self.mapper, mesh,
                                      spec=spec, shard_offset=0,
                                      placer=placer)
                packed.place_params = _make_tree_placer(
                    _full_placer(mesh, with_replica_dim=True))
                packed.place_aggs = _make_tree_placer(placer)
            else:
                placer = _row_placer(mesh, len(gmap), my_red_off, my_n)
                packed = PackedShards("mh", self.local_shards,
                                      self.mapper, mesh, spec=spec,
                                      shard_offset=my_red_off,
                                      placer=placer)
                pput = _param_placer(mesh, len(gmap), my_red_off, my_n)
                packed.place_params = _make_tree_placer(pput)
                packed.place_aggs = _make_tree_placer(placer)
            searcher = DistributedSearcher(packed)
        packed.place_step = _step_placer(mesh)
        nbytes = sum(
            int(getattr(leaf, "nbytes", 0))
            for leaf in jax.tree_util.tree_leaves((packed.dev,
                                                   packed.live)))
        hold = breaker_service().breaker("fielddata").hold(nbytes)
        weakref.finalize(packed, hold.release)
        return _MeshView(epoch, members, searcher, packed, hold,
                         gmap, dead_sids, owner)

    def _build_scoped_view(self, epoch: int, members: tuple) -> _MeshView:
        """Scoped-session serving state: the data plane is a mesh over
        MY OWN devices (mesh.local_mesh) running my span as a purely
        local program; the control plane carries raws, not collectives
        (_drive_scoped merges them). Member lifetimes are decoupled —
        the property the join handshake needs — and a membership-only
        rebuild is cheap: the local pack never changes, only the span
        maps and (shard layout) the spec's corpus stats do."""
        import weakref
        import jax
        from ..utils.breaker import breaker_service

        S_local = len(self.local_shards)
        mesh = local_mesh(S_local)
        placer = _full_placer(mesh)
        if self.layout == "replica":
            # every member holds everything: I serve (and fetch) every
            # sid locally, so a membership change cannot perturb a
            # single byte of my responses
            spec = PackSpec([self._summaries[self.my_id]], S_local)
            gmap = list(range(self.n_shards))
            dead_sids: list[int] = []
            owner = {s: self.my_id for s in gmap}
            offs = {h: 0 for h in members}
        else:
            gmap, owner, offs = [], {}, {}
            for h in [x for x in self.host_order if x in members]:
                off, n = self.offsets[h], self.host_shards[h]
                offs[h] = len(gmap)
                for s in range(off, off + n):
                    gmap.append(s)
                    owner[s] = h
            dead_sids = [s for s in range(self.n_shards)
                         if s not in owner]
            # GLOBAL corpus stats: total_docs (IDF) folds EVERY
            # member's summary even though only my span packs locally,
            # so scoped scores match the global-mesh program's
            spec = PackSpec([self._summaries[h]
                             for h in self.host_order if h in members],
                            S_local)
        packed = PackedShards("mh", self.local_shards, self.mapper,
                              mesh, spec=spec, shard_offset=0,
                              placer=placer)
        packed.place_params = _make_tree_placer(
            _full_placer(mesh, with_replica_dim=True))
        packed.place_aggs = _make_tree_placer(placer)
        packed.place_step = _step_placer(mesh)
        searcher = DistributedSearcher(packed)
        nbytes = sum(
            int(getattr(leaf, "nbytes", 0))
            for leaf in jax.tree_util.tree_leaves((packed.dev,
                                                   packed.live)))
        hold = breaker_service().breaker("fielddata").hold(nbytes)
        weakref.finalize(packed, hold.release)
        return _MeshView(epoch, members, searcher, packed, hold,
                         gmap, dead_sids, owner, scoped_offs=offs)

    # -- exec turn protocol ------------------------------------------------

    def _exec(self, view: _MeshView, seq: int, floor: int,
              bodies: list[dict], deadline: float | None,
              allow_stepped: bool | None,
              run_program: bool = True) -> list[dict]:
        """Every member must enter the same program in the same order —
        SPMD program entry is itself a collective. The turn is HELD
        only for the bookkeeping; raw_msearch runs with the condition
        released so blocked waiters can hit their deadlines promptly,
        and the turn advances even when the program raises (a wedged
        seq would starve every later exec).

        Under an ARMED stepped deadline the turn gate must NOT bail on
        the search deadline: the timeout decision is collective (the
        device-side psum'd verdict), so every member enters the
        program no matter how late — a member bailing at the gate
        while its peers entered would hang the collective on a real
        pod. Cooperative execs keep the prompt local bail."""
        self._turn_wait(view.epoch, seq, floor,
                        None if (allow_stepped or not run_program)
                        else deadline)
        try:
            if not run_program:
                # turn-only participant (single-process runtime: the
                # driver's entry executes every device's share)
                return []
            return view.searcher.raw_msearch(bodies, deadline=deadline,
                                             allow_stepped=allow_stepped)
        finally:
            self._turn_done(view.epoch, seq)

    def _turn_wait(self, epoch: int, seq: int, floor: int,
                   deadline: float | None) -> None:
        budget = time.monotonic() + self.timeouts["exec"]
        with self._exec_turn:
            if epoch == self._exec_epoch and floor > self._exec_floor:
                self._exec_floor = floor
                self._exec_turn.notify_all()
            while True:
                if epoch != self._exec_epoch:
                    raise StaleEpochError(
                        f"exec seq {seq} of epoch {epoch} fenced by "
                        f"epoch {self._exec_epoch}", epoch=epoch,
                        current=self._exec_epoch)
                if seq in self._abandoned:
                    # the driver aborted this broadcast after we
                    # accepted it: release NOW instead of riding out
                    # the exec budget (the PR 13 residual). If the
                    # abandoned seq held the next turn, advance past
                    # it so later seqs don't stall on the floor.
                    self._abandoned.discard(seq)
                    if not self._exec_running \
                            and seq == self._exec_next:
                        self._exec_next = seq + 1
                        self._exec_turn.notify_all()
                    raise StaleEpochError(
                        f"exec seq {seq} abandoned by its driver",
                        epoch=epoch, current=epoch)
                if not self._exec_running:
                    if self._exec_next < self._exec_floor:
                        # the driver promised no seq below the floor
                        # will ever arrive (abandoned broadcasts):
                        # skip the gap instead of wedging
                        self._exec_next = self._exec_floor
                    if seq < self._exec_next:
                        raise StaleEpochError(
                            f"exec seq {seq} replayed behind turn "
                            f"{self._exec_next}", epoch=epoch,
                            current=epoch)
                    if seq == self._exec_next:
                        self._exec_running = True
                        return
                # the search deadline lives on the (injectable) host
                # clock — msearch minted it there and peers corrected
                # onto it; the exec BUDGET is real wall time
                if deadline is not None \
                        and self._clock() > deadline:
                    raise SearchTimeoutError("mesh")
                if time.monotonic() > budget:
                    raise TimeoutError(
                        f"mesh exec {seq} never got its turn "
                        f"(next={self._exec_next})")
                self._exec_turn.wait(timeout=0.25)

    def _turn_done(self, epoch: int, seq: int) -> None:
        with self._exec_turn:
            self._exec_running = False
            if epoch == self._exec_epoch:
                self._exec_next = max(self._exec_next, seq + 1)
            self._exec_turn.notify_all()

    # -- driver API --------------------------------------------------------

    def _snapshot(self) -> _MeshView:
        with self._swap_mx:
            return self._view

    def _mint_seq(self, epoch: int) -> tuple[int, int]:
        # seed from the shared TURN counter, not just the local mint
        # counter: every broadcast in the epoch advanced _exec_next on
        # every member, so a DIFFERENT host taking over driving mints
        # from where the previous driver left off instead of replaying
        # behind the turn (driver handoff within an epoch). Concurrent
        # drivers are no longer best-effort: minting is gated on the
        # coordinator LEASE (_ensure_lease), and a broadcast carrying
        # a stale lease term is fenced 409 (LeaseFencedError) by every
        # peer's CoordinatorLease.fence before it can pair mismatched
        # programs in a collective — the fenced driver adopts the
        # newer term and retries through the lease.
        with self._exec_turn:
            turn = self._exec_next
        with self._exec_lock:
            seq = max(self._next_seq, turn)
            self._next_seq = seq + 1
            pend = self._outstanding.setdefault(epoch, set())
            pend.add(seq)
            return seq, min(pend)

    def _finish_seq(self, epoch: int, seq: int) -> None:
        with self._exec_lock:
            pend = self._outstanding.get(epoch)
            if pend is not None:
                pend.discard(seq)
                if not pend:
                    del self._outstanding[epoch]

    def msearch(self, bodies: list[dict],
                timeout: float | None = None) -> list[dict]:
        """Drive a batch through the current membership. `timeout`
        (seconds, relative) arms the deadline contract: with fresh
        clock offsets for every member the mesh runs the PREEMPTIVE
        stepped program (the device-side verdict 504s within
        deadline + clock-uncertainty pad); otherwise the timeout stays
        cooperative. Retries ride out membership swaps (StaleEpoch —
        incl. syncing a BEHIND driver forward) and flaky control-plane
        sends; a peer that times out the exec broadcast is marked dead
        on the spot.

        Contract: the single driver is ENFORCED by the coordinator
        lease — minting an exec seq requires holding it. Any host may
        drive: a non-holder first asks the holder to release (granted
        when idle), then wins a quorum election for the next term. A
        driver broadcasting under a superseded term is fenced 409
        (LeaseFencedError) by every peer before its program can enter
        a collective; the fenced driver adopts the newer term here and
        retries — closing the old concurrent-driver collision window
        for good."""
        from ..search.dispatch import membership_stats
        deadline = (self._clock() + timeout
                    if timeout is not None else None)
        last: Exception | None = None
        for attempt in range(max(4, self.fd["exec_retries"] * 2)):
            if attempt and deadline is not None \
                    and self._clock() > deadline:
                break
            view = self._snapshot()
            try:
                return self._drive_once(view, bodies, deadline)
            except StaleEpochError as e:
                last = e
                self._sync_epoch()
                time.sleep(min(0.05 * (attempt + 1), 0.5))
                continue
            except LeaseFencedError as e:
                # another driver holds (or took) the lease — remember
                # who, so the next attempt can request a handoff
                # instead of re-losing the election
                last = e
                membership_stats.fenced_drivers.inc()
                if e.term is not None:
                    self.lease.adopt(e.holder or "?", int(e.term))
                time.sleep(min(self.fd["exec_backoff"] * (attempt + 1),
                               0.5))
                continue
            except _RetryableExecError as e:
                last = e.cause
                if isinstance(e.cause, StaleEpochError):
                    # a PEER fenced my broadcast: I am the one behind
                    # (I never observed its membership transitions) —
                    # ask around and adopt forward before retrying
                    self._sync_epoch()
                elif isinstance(e.cause, LeaseFencedError):
                    # a peer knows a newer lease term than the one I
                    # broadcast under — adopt it; the next attempt
                    # goes through the handoff/election path
                    membership_stats.fenced_drivers.inc()
                    if e.cause.term is not None:
                        self.lease.adopt(e.cause.holder or "?",
                                         int(e.cause.term))
                # give detection/rebuild a beat before re-resolving
                # the membership
                time.sleep(min(self.fd["exec_backoff"] * (attempt + 1),
                               0.5))
                continue
        assert last is not None
        raise last

    def _sync_epoch(self) -> None:
        """A Stale rejection means someone numbered this membership
        higher than I did (I missed transitions while another host
        drove, or was the severed side of a partition that healed).
        Ping the members — the ping response carries (epoch, members)
        — and adopt a higher epoch over the SAME membership
        (renumber-only). A DIFFERENT membership at a higher epoch is
        folded through the ledger in quorum mode (the healed side of a
        partition syncs forward to the majority's committed epoch —
        the minority never committed anything of its own to undo); in
        health mode it converges through detection/rebuild instead."""
        for h in [x for x in self.members if x != self.my_id]:
            try:
                resp = self._ctrl_send(h, MESH_PING_ACTION,
                                       {"host": self.my_id},
                                       timeout=self.fd["ping_timeout"])
            except Exception:  # noqa: BLE001 — detection's job
                continue
            r_members = tuple(resp.get("members") or ())
            r_epoch = int(resp.get("epoch", -1))
            with self._swap_mx:
                same = r_members == self._view.members
                behind = r_epoch > self._view.epoch
                if same and behind:
                    self._adopt_epoch_locked(r_epoch)
            if not same and behind \
                    and self.membership_mode == "quorum":
                self._fold_commit(r_epoch, r_members,
                                  host_shards=dict(self.host_shards))

    def _ensure_lease(self, view: _MeshView) -> None:
        """Hold the coordinator lease before minting exec seqs. A
        non-holder first asks the current holder to step down (granted
        when it has no outstanding seqs), then runs a quorum election
        for the next term; a dead holder's lease simply expires and
        the election proceeds without the handoff. Raises
        LeaseFencedError when a live holder refuses — msearch backs
        off and retries (the 409-and-retry contract)."""
        if self.lease.i_hold():
            return
        holder, _term = self.lease.holder()
        handoff = None
        if holder is not None and holder != self.my_id \
                and holder in self.ledger.committed().members \
                and not self.health.dead_rows() & {
                    self._host_idx(holder)}:
            # only a live committed member is worth asking; an evicted
            # or known-dead holder's lease is vacated by the quorum
            # decision / covered by expiry failover
            try:
                if self.coord.request_handoff(holder):
                    handoff = holder
            except Exception:  # noqa: BLE001 — dead holder: expiry
                pass           # handles it; election proceeds below
        self.coord.acquire_lease(self._current_epoch(),
                                 handoff_from=handoff)

    def _drive_once(self, view: _MeshView, bodies: list[dict],
                    deadline: float | None) -> list[dict]:
        if self.session == "scoped":
            return self._drive_scoped(view, bodies, deadline)
        self._ensure_lease(view)
        holder, term = self.lease.holder()
        seq, floor = self._mint_seq(view.epoch)
        peers = [h for h in view.members if h != self.my_id]
        stepped = (deadline is not None
                   and self.clock_table.fresh(
                       peers, self.fd["clock_max_uncertainty"]))
        payload = {"seq": seq, "floor": floor, "epoch": view.epoch,
                   "members": list(view.members),
                   "bodies": json.dumps(bodies),
                   "deadline": deadline, "stepped": stepped,
                   "lease_holder": holder, "lease_term": term}
        notified: list[str] = []
        try:
            # pre-flight: a KNOWN-dead member (injected machine death)
            # must abort the broadcast BEFORE any peer is notified —
            # peers that already accepted would enter the collective
            # and wedge when the driver then abandons the seq. (A peer
            # that turns unreachable mid-broadcast can still leave
            # that window open until detection shrinks the membership;
            # the stepped deadline bounds the wedge when armed.)
            for h in peers:
                if faults.host_dead_matches(h):
                    raise _RetryableExecError(RuntimeError(
                        f"member [{h}] is known dead; awaiting "
                        "eviction"))
            futures = {}
            try:
                for h in peers:
                    fut = self._submit_exec(h, payload)
                    if isinstance(fut, Exception):
                        # the peer is unreachable after every retry:
                        # do NOT enter the SPMD program (on a real pod
                        # the collective would hang on the missing
                        # member) — health has the failure; detection/
                        # rebuild will shrink the membership and the
                        # driver retries
                        raise _RetryableExecError(fut)
                    futures[h] = fut
                    notified.append(h)
                raws = self._exec(view, seq, floor, bodies, deadline,
                                  stepped if deadline is not None
                                  else None)
                for h, fut in futures.items():
                    try:
                        fut.result(timeout=self.timeouts["exec"])
                    except SearchTimeoutError:
                        # the peer's (offset-corrected) deadline
                        # verdict: the search IS timed out — not a
                        # liveness signal, not retryable
                        raise
                    except StaleEpochError as e:
                        raise _RetryableExecError(e) from e
                    except LeaseFencedError as e:
                        # the peer knows a newer lease term: my lease
                        # is superseded — adopt and re-elect (msearch)
                        raise _RetryableExecError(e) from e
                    except (TimeoutError, _FUT_TIMEOUT) as e:
                        # accepted the broadcast, never finished: a
                        # wedged peer hangs every later collective —
                        # one occurrence is conclusive (zen-fd's ping-
                        # handler timeout analog). mark_dead's on_dead
                        # hook records the evict_host decision.
                        self.health.mark_dead(self._host_idx(h))
                        raise _RetryableExecError(e) from e
                    except Exception as e:  # noqa: BLE001 — ctrl
                        self.health.record_failure(self._host_idx(h), e)
                        raise _RetryableExecError(e) from e
                    # a completed exec round trip proves liveness:
                    # reset the consecutive count so scattered
                    # transient drops across many searches never
                    # accumulate to an evict
                    self.health.record_success(self._host_idx(h))
            except BaseException:
                # this driver is bailing on the broadcast: tell every
                # peer that already accepted it to release the seq NOW
                # (ABANDON) instead of riding out its exec budget —
                # the prompt close of the mid-broadcast residual (the
                # budget/floor fallbacks still stand for a driver that
                # dies before it can say so)
                self._abandon_seq(view.epoch, seq, notified)
                raise
            # a fully-acked broadcast doubles as a lease renewal: an
            # active driver never loses its lease to expiry mid-load
            self.lease.adopt(self.my_id, term)
        finally:
            # the floor only rises once this seq can no longer reach
            # a peer — keep it outstanding until every future settled
            self._finish_seq(view.epoch, seq)
        if deadline is not None and self._clock() > deadline:
            # cooperative backstop (the stepped verdict raises from
            # the collect itself; this covers unfused/unstepped plans)
            raise SearchTimeoutError(view.packed.index_name)
        return [self._build_response(b, raw, view)
                for b, raw in zip(bodies, raws)]

    def _abandon_seq(self, epoch: int, seq: int,
                     hosts: list[str]) -> None:
        """Best-effort ABANDON broadcast: peers that accepted `seq`
        release it immediately instead of waiting out the exec budget
        (closing the PR 13 mid-broadcast residual promptly). Failures
        are swallowed — an unreachable peer falls back to the budget/
        floor machinery this replaces on the fast path."""
        for h in hosts:
            try:
                self._ctrl_send(h, MESH_ABANDON_ACTION,
                                {"epoch": epoch, "seq": seq},
                                timeout=self.fd["ping_timeout"])
            except Exception:  # noqa: BLE001 — best-effort by design
                pass

    def _drive_scoped(self, view: _MeshView, bodies: list[dict],
                      deadline: float | None) -> list[dict]:
        """Drive a batch through scoped per-member device runtimes: no
        SPMD collective ties the members, so a broadcast leg that
        fails DEGRADES (that member's shard span becomes structured
        `_shards.failures`) instead of wedging the pod. The lease
        still gates driving (one merge authority at a time) and epoch
        fencing still rejects stale members; the exec-turn machinery
        is skipped — local programs cannot cross-pair."""
        self._ensure_lease(view)
        holder, term = self.lease.holder()
        # an outstanding seq marks this driver busy: the lease-release
        # handler refuses handoffs mid-drive (no merge authority swap
        # while legs are in flight)
        seq, _floor = self._mint_seq(view.epoch)
        span_failures: dict[str, Exception] = {}
        try:
            peers = ([] if self.layout == "replica"
                     else [h for h in view.members if h != self.my_id])
            stepped = (deadline is not None
                       and (not peers or self.clock_table.fresh(
                           peers, self.fd["clock_max_uncertainty"])))
            payload = {"scoped": True, "epoch": view.epoch,
                       "members": list(view.members),
                       "bodies": json.dumps(bodies),
                       "deadline": deadline, "stepped": stepped,
                       "lease_holder": holder, "lease_term": term}
            futures = {}
            for h in peers:
                if faults.host_dead_matches(h) \
                        or faults.net_partition_matches(self.my_id, h):
                    e: Exception = HostDownError(h)
                    self.health.record_failure(self._host_idx(h), e)
                    span_failures[h] = e
                    continue
                fut = self._submit_exec(h, payload)
                if isinstance(fut, Exception):
                    span_failures[h] = fut
                    continue
                futures[h] = fut
            per_host = {self.my_id: view.searcher.raw_msearch(
                bodies, deadline=deadline,
                allow_stepped=(stepped if deadline is not None
                               else None))}
            for h, fut in futures.items():
                try:
                    r = fut.result(timeout=self.timeouts["exec"])
                except SearchTimeoutError:
                    raise
                except StaleEpochError as e2:
                    raise _RetryableExecError(e2) from e2
                except LeaseFencedError as e2:
                    raise _RetryableExecError(e2) from e2
                except (TimeoutError, _FUT_TIMEOUT) as e2:
                    self.health.mark_dead(self._host_idx(h))
                    span_failures[h] = e2
                    continue
                except Exception as e2:  # noqa: BLE001 — degrade
                    self.health.record_failure(self._host_idx(h), e2)
                    span_failures[h] = e2
                    continue
                per_host[h] = r["raws"]
                self.health.record_success(self._host_idx(h))
            raws = self._merge_scoped(view, bodies, per_host)
            self.lease.adopt(self.my_id, term)
        finally:
            self._finish_seq(view.epoch, seq)
        if deadline is not None and self._clock() > deadline:
            raise SearchTimeoutError(view.packed.index_name)
        return [self._build_response(b, raw, view,
                                     span_failures=span_failures)
                for b, raw in zip(bodies, raws)]

    def _merge_scoped(self, view: _MeshView, bodies: list[dict],
                      per_host: dict) -> list[dict]:
        """Host-side cross-member merge — the SearchPhaseController
        analog the collective used to run on-device. Replica layout:
        the driver's own full-copy results ARE the answer (that is
        what makes replica serving byte-identical through membership
        changes). Shard layout: concatenate the members' candidate
        lists (local shard ids lifted by each member's span offset
        into the driver's reduced space), re-sort by (-score, global
        sid, doc) — the same total order the packed reduce yields —
        and merge agg partials with the generation-merge semantics
        (associative over disjoint doc sets)."""
        if self.layout == "replica":
            return per_host[self.my_id]
        gmap = np.asarray(view.gmap, dtype=np.int64)
        out: list[dict] = []
        for i in range(len(bodies)):
            mine = per_host[self.my_id][i]
            specs = mine["agg_specs"]
            scs, shs, dcs, parts = [], [], [], []
            total = 0
            for h in self.host_order:
                if h not in per_host:
                    continue
                r = per_host[h][i]
                sc = np.asarray(r["score"], dtype=np.float32)
                sh = np.asarray(r["shard"], dtype=np.int64)
                dc = np.asarray(r["doc"], dtype=np.int64)
                nv = int(min(int(r["total"]), sc.shape[0]))
                scs.append(sc[:nv])
                shs.append(sh[:nv] + int(view.scoped_offs[h]))
                dcs.append(dc[:nv])
                total += int(r["total"])
                if r.get("partials") is not None:
                    parts.append(r["partials"])
            sc = (np.concatenate(scs) if scs
                  else np.zeros(0, np.float32))
            sh = (np.concatenate(shs) if shs
                  else np.zeros(0, np.int64))
            dc = (np.concatenate(dcs) if dcs
                  else np.zeros(0, np.int64))
            order = np.lexsort((dc, gmap[sh] if sh.size else sh, -sc))
            if len(parts) > 1:
                partials = merge_shard_partials(specs, parts)
            else:
                partials = parts[0] if parts else None
            out.append({"score": sc[order], "shard": sh[order],
                        "doc": dc[order], "total": total,
                        "partials": partials, "agg_specs": specs})
        return out

    def _submit_exec(self, host: str, payload: dict):
        """Per-peer exec send with retry/backoff: a transient
        ctrl_drop (or TCP hiccup) must not fail the search, and a
        persistently unreachable peer feeds the health tracker.
        Returns the pending Future, or the last Exception when every
        attempt failed."""
        last: Exception | None = None
        for attempt in range(max(1, self.fd["exec_retries"])):
            if attempt:
                time.sleep(self.fd["exec_backoff"] * (2 ** (attempt - 1)))
            try:
                fut = self._ctrl_submit(host, MESH_EXEC_ACTION, payload,
                                        timeout=self.timeouts["exec"])
            except Exception as e:  # noqa: BLE001 — injected/ctrl
                last = e
                continue
            if fut.done() and fut.exception() is not None:
                exc = fut.exception()
                if isinstance(exc, (StaleEpochError, LeaseFencedError)):
                    # not a liveness problem — surface to the driver
                    return fut
                last = exc
                continue
            return fut
        assert last is not None
        self.health.record_failure(self._host_idx(host), last)
        return last

    def search(self, body: dict, timeout: float | None = None) -> dict:
        return self.msearch([body], timeout=timeout)[0]

    # -- response building -------------------------------------------------

    def _build_response(self, body: dict, raw: dict,
                        view: _MeshView,
                        span_failures: dict | None = None) -> dict:
        frm = int(body.get("from", 0))
        size = int(body.get("size", 10))
        nvalid = int(min(raw["total"], raw["score"].shape[0]))
        window = [(float(raw["score"][j]),
                   view.gmap[int(raw["shard"][j])],
                   int(raw["doc"][j]))
                  for j in range(nvalid)][frm: frm + size]
        # group the fetch by owning host (the distributed FetchPhase)
        per_host: dict[str, list[tuple[int, int]]] = {}
        for _sc, s, d in window:
            per_host.setdefault(view.owner_by_sid[s], []).append((s, d))
        fetched: dict[tuple[int, int], tuple[str, str]] = {}
        failures = [shard_failure(s, view.packed.index_name,
                                  HostDownError(
                                      self._dead_owner_of(s), shard=s),
                                  node=self._dead_owner_of(s))
                    for s in view.dead_sids]
        # scoped sessions degrade per-LEG: a member whose broadcast
        # leg failed contributes no candidates, so its whole span is
        # reported failed for THIS response (not evicted — detection
        # owns membership)
        down_sids: set[int] = set()
        for h, e in (span_failures or {}).items():
            for s, owner in view.owner_by_sid.items():
                if owner == h and s not in view.dead_sids:
                    down_sids.add(s)
                    failures.append(shard_failure(
                        s, view.packed.index_name, e, node=h))
        fetch_failed_sids: set[int] = set()
        for h, docs in per_host.items():
            try:
                if h == self.my_id:
                    resp = {"docs": self._fetch_docs(view, docs)}
                else:
                    resp = self._ctrl_send(
                        h, MESH_FETCH_ACTION,
                        {"docs": docs, "epoch": view.epoch},
                        timeout=self.timeouts["fetch"])
            except Exception as e:  # noqa: BLE001 — degrade, don't die
                # the owner died (or dropped the fetch) between exec
                # and fetch: those hits become structured failures —
                # a partial response instead of a failed search
                self.health.record_failure(self._host_idx(h), e)
                for s in sorted({s for s, _d in docs}):
                    fetch_failed_sids.add(s)
                    failures.append(shard_failure(
                        s, view.packed.index_name, e, node=h))
                continue
            for (s, d), payload in zip(docs, resp["docs"]):
                fetched[(s, d)] = tuple(payload)
        hits = []
        for sc, s, d in window:
            if (s, d) not in fetched:
                continue
            did, src = fetched[(s, d)]
            hits.append({"_index": view.packed.index_name,
                         "_type": "_doc", "_id": did, "_score": sc,
                         "_source": json.loads(src) if src else {}})
        successful = self.n_shards - len(view.dead_sids) \
            - len(down_sids) - len(fetch_failed_sids)
        resp = {
            "took": 0, "timed_out": False,
            "_shards": shards_header(self.n_shards, successful,
                                     failures=failures),
            "hits": {"total": raw["total"],
                     "max_score": (float(raw["score"][0])
                                   if nvalid else None),
                     "hits": hits},
        }
        if raw["agg_specs"]:
            merged = merge_shard_partials(raw["agg_specs"],
                                          [raw["partials"]])
            resp["aggregations"] = finalize_partials(raw["agg_specs"],
                                                     merged)
        return resp

    def _dead_owner_of(self, global_sid: int) -> str:
        for h in self.host_order:
            off = self.offsets[h]
            if off <= global_sid < off + self.host_shards[h]:
                return h
        return "?"

    # -- introspection / lifecycle ----------------------------------------

    @property
    def epoch(self) -> int:
        return self._snapshot().epoch

    @property
    def members(self) -> tuple:
        return self._snapshot().members

    def stats(self) -> dict:
        view = self._snapshot()
        return {"epoch": view.epoch, "members": list(view.members),
                "dead_hosts": [self.host_order[i]
                               for i in sorted(self.health.dead_rows())],
                "dead_shards": list(view.dead_sids),
                "layout": self.layout,
                "session": self.session,
                "membership": self.membership_mode,
                "lease": self.lease.snapshot(),
                "ledger": self.ledger.snapshot(),
                "drained_hosts": [
                    self.host_order[i]
                    for i in sorted(self.health.excluded_rows())],
                "clock": self.clock_table.snapshot(),
                "decisions": len(self.decisions)}

    def await_settled(self, timeout: float = 30.0) -> bool:
        """Block until no rebuild thread runs AND the served members
        match the health state. Test hook — production callers never
        wait on the lifecycle."""
        cutoff = time.monotonic() + timeout
        while time.monotonic() < cutoff:
            want = self._alive_members()
            with self._swap_mx:
                settled = self._view.members == want
                busy = any(t.is_alive() for t in self._threads
                           if t.name.startswith("mesh-rebuild"))
            if settled and not busy:
                return True
            time.sleep(0.005)
        return False

    def close(self) -> None:
        self._closed.set()
        self.lease.release()
        self.await_settled(timeout=5.0)
        with self._swap_mx:
            hold = self._view.hold
        if hold is not None:
            hold.release()


class _RetryableExecError(Exception):
    """Internal: one drive attempt failed in a way a membership swap
    or a backoff can fix; msearch's outer loop retries."""

    def __init__(self, cause: Exception):
        super().__init__(repr(cause))
        self.cause = cause


def _make_tree_placer(place):
    import jax

    def placer(tree):
        return jax.tree_util.tree_map(place, tree)

    return placer
