"""Multi-host device-mesh execution: the DCN data plane.

Reference analog: the reference scales search across machines by RPC
fan-out + coordinator merge (action/search/type/
TransportSearchTypeAction.java:126-148) over its Netty transport with
per-shard results reduced host-side
(search/controller/SearchPhaseController.java:147-282).

TPU-first redesign (SURVEY §7 step 6): processes join ONE
jax.distributed runtime; their local devices form a single global
("replica", "shard") Mesh; each host packs ITS shards' columns into the
global mesh arrays (jax.make_array_from_callback serves only the rows
this host owns); a search is then ONE SPMD program whose cross-shard
top-k/agg reduce rides XLA collectives — ICI within a host, DCN between
hosts — instead of application-level RPC merging.

The cluster transport (cluster/transport.py LocalHub or
cluster/tcp_transport.py) remains the CONTROL plane:
  * pack-spec agreement: hosts exchange shard summaries
    (distributed.summarize_shards) and each derives the identical
    PackSpec — only metadata crosses the control plane, never columns;
  * program entry: SPMD requires every process to enter the same
    compiled call, so the driver broadcasts "mesh:exec" and every host
    calls into the same program in sequence order;
  * fetch: hits live on the owning host; the driver fetches _id/_source
    by (shard, row) over "mesh:fetch" — the only per-query
    host-to-host data besides the in-program collectives.

Hardware note: this module is exercised on a multi-process CPU mesh
(tests/test_multihost.py spawns real OS processes with
xla_force_host_platform_device_count; collectives ride Gloo). On TPU
pods the same code path uses the ICI/DCN collectives — the mesh shape
is the only difference.
"""

from __future__ import annotations

import json
import threading

import numpy as np

from .distributed import (PackedShards, PackSpec, DistributedSearcher,
                          summarize_shards, merge_shard_partials,
                          finalize_partials)
from ..search.controller import shards_header
from ..utils.settings import Settings, parse_time_value

MESH_SUMMARY_ACTION = "internal:mesh/summary"
MESH_EXEC_ACTION = "internal:mesh/exec"
MESH_FETCH_ACTION = "internal:mesh/fetch"


def mesh_timeouts(settings: "Settings | None" = None) -> dict:
    """Control-plane wait budgets in SECONDS, settings-driven so slow
    pods (cold container starts, big packs crossing DCN) can stretch
    them instead of hard-failing packing on the old literals.

    * `mesh.pack_send_timeout`  — one summary send attempt (was 5s)
    * `mesh.pack_sync_timeout`  — all peers' summaries + the peer
      handler-registration retry window (was 60s / 30s)
    * `mesh.exec_timeout`       — SPMD entry turn + remote exec ack +
      pack-ready gate (was 120s)
    * `mesh.fetch_timeout`      — one cross-host fetch (was 30s)
    """
    s = settings or Settings.EMPTY
    ms = {"pack_send": parse_time_value(
              s.get("mesh.pack_send_timeout"), 5_000),
          "pack_sync": parse_time_value(
              s.get("mesh.pack_sync_timeout"), 60_000),
          "exec": parse_time_value(s.get("mesh.exec_timeout"), 120_000),
          "fetch": parse_time_value(s.get("mesh.fetch_timeout"), 30_000)}
    return {k: v / 1000.0 for k, v in ms.items()}


def init_multihost(coordinator_address: str, num_processes: int,
                   process_id: int, platform: str | None = None) -> None:
    """Join the jax.distributed runtime (idempotent). Must run before
    any other jax API touches the backend."""
    import jax
    if platform:
        jax.config.update("jax_platforms", platform)
    if getattr(init_multihost, "_done", False):  # pragma: no cover
        return
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)
    init_multihost._done = True  # type: ignore[attr-defined]


def global_mesh(n_shards: int):
    """One mesh over every process's devices, shard axis process-major
    (process p's local devices own a contiguous shard-row span)."""
    import jax
    from jax.sharding import Mesh
    devs = sorted(jax.devices(), key=lambda d: (d.process_index, d.id))
    if n_shards != len(devs):
        raise ValueError(f"multi-host mesh wants one device per shard "
                         f"({n_shards} shards, {len(devs)} devices)")
    return Mesh(np.asarray(devs).reshape(1, n_shards),
                axis_names=("replica", "shard"))


def _row_placer(mesh, n_shards: int, offset: int, n_local: int):
    """Placer serving only this host's shard rows [offset, offset+n_local)
    of global [n_shards, ...] arrays."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    def place(local: np.ndarray):
        shape = (n_shards,) + local.shape[1:]
        sharding = NamedSharding(
            mesh, P("shard", *([None] * (local.ndim - 1))))

        def cb(index):
            rows = index[0]
            lo = 0 if rows.start is None else rows.start
            hi = shape[0] if rows.stop is None else rows.stop
            if lo < offset or hi > offset + n_local:
                raise RuntimeError(
                    f"device asked for shard rows [{lo}:{hi}) outside "
                    f"this host's span [{offset}:{offset + n_local})")
            return local[(slice(lo - offset, hi - offset),)
                         + tuple(index[1:])]

        return jax.make_array_from_callback(shape, sharding, cb)

    return place


def _param_placer(mesh, n_shards: int, offset: int, n_local: int):
    """Like _row_placer but for query params [S_local, B, ...] with
    P("shard", "replica") — the replica axis is 1 in multi-host meshes,
    so the batch dim is fully replicated per shard row."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    def place(local):
        local = np.asarray(local)
        shape = (n_shards,) + local.shape[1:]
        sharding = NamedSharding(
            mesh, P("shard", "replica",
                    *([None] * (local.ndim - 2))))

        def cb(index):
            rows = index[0]
            lo = 0 if rows.start is None else rows.start
            hi = shape[0] if rows.stop is None else rows.stop
            if lo < offset or hi > offset + n_local:
                raise RuntimeError(
                    f"device asked for shard rows [{lo}:{hi}) outside "
                    f"this host's span [{offset}:{offset + n_local})")
            return local[(slice(lo - offset, hi - offset),)
                         + tuple(index[1:])]

        return jax.make_array_from_callback(shape, sharding, cb)

    return place


class MultiHostIndex:
    """A mesh index whose shards live on different hosts.

    All hosts construct this with the SAME global shard layout
    (host_shards: {host_id: n_shards_owned}, iterated in host_order).
    Searches are driven from any single host via msearch(); the other
    hosts join the SPMD program through the control-plane exec
    broadcast.
    """

    def __init__(self, transport, my_id: str, host_order: list[str],
                 local_shards, mapper, host_shards: dict[str, int],
                 settings: "Settings | None" = None):
        # wait budgets FIRST: control-plane handlers registered below
        # may fire (from a faster host) before __init__ finishes
        self.timeouts = mesh_timeouts(settings)
        self.transport = transport
        self.my_id = my_id
        self.host_order = list(host_order)
        self.peers = [h for h in host_order if h != my_id]
        self.n_shards = sum(host_shards.values())
        self.host_shards = dict(host_shards)
        offsets: dict[str, int] = {}
        off = 0
        for h in host_order:
            offsets[h] = off
            off += host_shards[h]
        self.offsets = offsets
        self.my_offset = offsets[my_id]
        if len(local_shards) != host_shards[my_id]:
            raise ValueError("local shard count != declared host_shards")

        # -- control plane: summary allgather -> identical PackSpec ----
        self._summaries: dict[str, dict] = {}
        self._summaries_ready = threading.Event()
        self._exec_results: dict[int, list] = {}
        self._exec_done: dict[int, threading.Event] = {}
        self._exec_lock = threading.Lock()
        self._next_seq = 0
        self._exec_turn = threading.Condition()
        self._exec_next = 0
        # exec/fetch arrive as soon as a FASTER host finishes its own
        # __init__; they must wait until this host's pack exists
        self._ready = threading.Event()
        transport.register_handler(MESH_SUMMARY_ACTION, self._on_summary)
        transport.register_handler(MESH_EXEC_ACTION, self._on_exec)
        transport.register_handler(MESH_FETCH_ACTION, self._on_fetch)

        mine = summarize_shards(local_shards)
        self._accept_summary(my_id, mine)
        import time
        for h in self.peers:
            deadline = time.time() + self.timeouts["pack_sync"]
            while True:  # peers may still be registering handlers
                try:
                    transport.send_request(h, MESH_SUMMARY_ACTION,
                                           {"host": my_id,
                                            "summary": mine},
                                           timeout=self.timeouts[
                                               "pack_send"])
                    break
                except Exception:
                    if time.time() > deadline:
                        raise
                    time.sleep(0.2)
        if not self._summaries_ready.wait(
                timeout=self.timeouts["pack_sync"]):
            missing = set(host_order) - set(self._summaries)
            raise TimeoutError(f"pack summaries missing from {missing}")
        spec = PackSpec([self._summaries[h] for h in host_order],
                        self.n_shards)

        # -- data plane: local rows into the global mesh ---------------
        mesh = global_mesh(self.n_shards)
        self.mesh = mesh
        n_local = host_shards[my_id]
        placer = _row_placer(mesh, self.n_shards, self.my_offset, n_local)
        self.packed = PackedShards("mh", local_shards, mapper, mesh,
                                   spec=spec, shard_offset=self.my_offset,
                                   placer=placer)
        pput = _param_placer(mesh, self.n_shards, self.my_offset, n_local)
        import jax
        self.packed.place_params = lambda tree: jax.tree_util.tree_map(
            pput, tree)
        # agg params are shard-row tensors too ([S_local, ...])
        self.packed.place_aggs = lambda tree: jax.tree_util.tree_map(
            placer, tree)
        self.searcher = DistributedSearcher(self.packed)
        self._ready.set()

    # -- control-plane handlers -------------------------------------------

    def _accept_summary(self, host: str, summary: dict) -> None:
        self._summaries[host] = summary
        if set(self._summaries) >= set(self.host_order):
            self._summaries_ready.set()

    def _on_summary(self, src: str, req: dict) -> dict:
        self._accept_summary(req["host"], req["summary"])
        return {"ok": True}

    def _on_exec(self, src: str, req: dict) -> dict:
        if not self._ready.wait(timeout=self.timeouts["exec"]):
            raise TimeoutError("mesh host never finished packing")
        self._exec(int(req["seq"]), json.loads(req["bodies"]))
        return {"ok": True}

    def _on_fetch(self, src: str, req: dict) -> dict:
        if not self._ready.wait(timeout=self.timeouts["exec"]):
            raise TimeoutError("mesh host never finished packing")
        out = []
        for shard, row in req["docs"]:
            seg = self.packed.shards[int(shard) - self.my_offset]
            out.append((seg.ids[int(row)],
                        seg.sources[int(row)].decode("utf-8",
                                                     "replace")))
        return {"docs": out}

    def _exec(self, seq: int, bodies: list[dict]) -> list[dict]:
        """Every host must enter the same program in the same order —
        SPMD program entry is itself a collective."""
        import time
        deadline = time.time() + self.timeouts["exec"]
        with self._exec_turn:
            while seq != self._exec_next:
                if time.time() > deadline:
                    raise TimeoutError(
                        f"mesh exec {seq} never got its turn "
                        f"(next={self._exec_next})")
                self._exec_turn.wait(timeout=5.0)
            raws = self.searcher.raw_msearch(bodies)
            self._exec_next = seq + 1
            self._exec_turn.notify_all()
        return raws

    # -- driver API --------------------------------------------------------

    def msearch(self, bodies: list[dict]) -> list[dict]:
        with self._exec_lock:
            seq = self._next_seq
            self._next_seq += 1
        payload = {"seq": seq, "bodies": json.dumps(bodies)}
        futures = [self.transport.submit_request(
                       h, MESH_EXEC_ACTION, payload,
                       timeout=self.timeouts["exec"])
                   for h in self.peers]
        raws = self._exec(seq, bodies)  # joins the SPMD program
        for f in futures:
            f.result(timeout=self.timeouts["exec"])
        return [self._build_response(b, raw)
                for b, raw in zip(bodies, raws)]

    def search(self, body: dict) -> dict:
        return self.msearch([body])[0]

    def _owner_of(self, shard: int) -> str:
        for h in self.host_order:
            off = self.offsets[h]
            if off <= shard < off + self.host_shards[h]:
                return h
        raise ValueError(f"shard {shard} outside mesh")

    def _build_response(self, body: dict, raw: dict) -> dict:
        frm = int(body.get("from", 0))
        size = int(body.get("size", 10))
        nvalid = int(min(raw["total"], raw["score"].shape[0]))
        window = [(float(raw["score"][j]), int(raw["shard"][j]),
                   int(raw["doc"][j]))
                  for j in range(nvalid)][frm: frm + size]
        # group the fetch by owning host (the distributed FetchPhase)
        per_host: dict[str, list[tuple[int, int]]] = {}
        for _sc, s, d in window:
            per_host.setdefault(self._owner_of(s), []).append((s, d))
        fetched: dict[tuple[int, int], tuple[str, str]] = {}
        for h, docs in per_host.items():
            if h == self.my_id:
                resp = self._on_fetch(self.my_id, {"docs": docs})
            else:
                resp = self.transport.send_request(
                    h, MESH_FETCH_ACTION, {"docs": docs},
                    timeout=self.timeouts["fetch"])
            for (s, d), payload in zip(docs, resp["docs"]):
                fetched[(s, d)] = tuple(payload)
        hits = []
        for sc, s, d in window:
            did, src = fetched[(s, d)]
            hits.append({"_index": self.packed.index_name,
                         "_type": "_doc", "_id": did, "_score": sc,
                         "_source": json.loads(src) if src else {}})
        resp = {
            "took": 0, "timed_out": False,
            "_shards": shards_header(self.n_shards, self.n_shards),
            "hits": {"total": raw["total"],
                     "max_score": (float(raw["score"][0])
                                   if nvalid else None),
                     "hits": hits},
        }
        if raw["agg_specs"]:
            merged = merge_shard_partials(raw["agg_specs"],
                                          [raw["partials"]])
            resp["aggregations"] = finalize_partials(raw["agg_specs"],
                                                     merged)
        return resp
