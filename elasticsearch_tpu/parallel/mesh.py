"""Device mesh construction for distributed search.

The mesh has two axes:
  * "replica" — data parallelism over QUERIES (a batch of requests is
    split across replica rows; the index is replicated). The analog of
    the reference's replica copies serving read throughput
    (cluster/routing/Preference.java round-robin over copies).
  * "shard"   — the index partition axis (hash-routed document shards,
    ref OperationRouting.java). Columns live sharded over this axis;
    the shard-reduce (SearchPhaseController analog) runs over it with
    ICI collectives.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh


def build_mesh(n_shards: int, n_replicas: int = 1,
               devices: list | None = None) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    need = n_shards * n_replicas
    if len(devices) < need:
        raise ValueError(
            f"mesh needs {need} devices (replica {n_replicas} x shard "
            f"{n_shards}), have {len(devices)}")
    arr = np.asarray(devices[:need]).reshape(n_replicas, n_shards)
    return Mesh(arr, axis_names=("replica", "shard"))


def reduced_mesh(mesh: Mesh, dead_rows: set[int] | frozenset[int]) -> Mesh:
    """The FULL mesh minus the given (physical) replica rows — the
    degraded mesh a live repack (parallel/repack.py) re-packs onto when
    a device in those rows is evicted. The shard axis is untouched:
    eviction loses replication, never index coverage. Raises when no
    row survives (an index with zero copies cannot serve; callers keep
    the old pack and keep paying failover instead)."""
    rows = [r for r in range(mesh.shape["replica"]) if r not in dead_rows]
    if not rows:
        raise ValueError("cannot reduce a mesh to zero replica rows")
    arr = np.asarray(mesh.devices)[rows, :]
    return Mesh(arr, axis_names=("replica", "shard"))


def host_mesh(device_rows) -> Mesh:
    """Mesh over explicit per-host device rows — the multihost
    membership mesh (parallel/multihost.py). Full membership stacks
    every member host's device row; the REDUCED host mesh after an
    eviction stacks only the survivors' rows, extending `reduced_mesh`
    from replica rows to whole machines:

      * replica layout — one row per host, every host a full copy of
        the shard axis: a dead host removes its row, coverage intact;
      * shard layout  — ONE row whose columns concatenate the member
        hosts' shard spans: a dead host removes its columns and the
        lost shards degrade to structured `_shards.failures` partials.

    Raises when no row/column survives (a mesh serving nothing)."""
    arr = np.asarray(device_rows)
    if arr.size == 0:
        raise ValueError("cannot build a host mesh with zero devices")
    return Mesh(arr, axis_names=("replica", "shard"))


def local_mesh(n_shards: int, devices: list | None = None) -> Mesh:
    """Mesh over THIS PROCESS's devices only — the scoped-session data
    plane (parallel/multihost.py session="scoped"): each member runs
    its shard span as a purely local program and the control plane
    merges raw results host-side, so no cross-process collective (and
    no shared jax.distributed runtime) ties member lifetimes together.
    That is what lets a replacement process join a live pod: its device
    runtime is its own, scoped to its membership epoch, and survivors
    never re-initialize theirs. One replica row, one column per local
    shard (the pack layout requires a column per packed segment, same
    as the global mesh requires one per member shard)."""
    devices = devices if devices is not None else jax.local_devices()
    if len(devices) < n_shards:
        raise ValueError(
            f"scoped mesh needs {n_shards} local devices (one per local "
            f"shard), have {len(devices)}")
    arr = np.asarray(devices[:n_shards]).reshape(1, n_shards)
    return Mesh(arr, axis_names=("replica", "shard"))


def default_mesh(n_devices: int | None = None) -> Mesh:
    """Mesh over all (or n) devices: replica axis gets the factor of 2
    when the device count allows, the rest goes to shards."""
    devices = jax.devices()
    n = n_devices or len(devices)
    n_replicas = 2 if n % 2 == 0 and n >= 4 else 1
    return build_mesh(n // n_replicas, n_replicas, devices[:n])
