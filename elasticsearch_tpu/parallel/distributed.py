"""Mesh-distributed search: shard-parallel scoring with in-program reduce.

Reference analog: the distributed QUERY phase — TransportSearchAction
fanning out to one copy of every shard (TransportSearchTypeAction.java:
126-153) and SearchPhaseController merging shard top-k + agg trees on a
coordinating node (SearchPhaseController.java:147-282).

TPU-first redesign: instead of RPC fan-out + host merge, the WHOLE
distributed query is ONE jitted program over a ("replica", "shard")
mesh via shard_map:

    each device scores ITS shard's columns locally        (QueryPhase)
    lax.all_gather of local top-k over the "shard" axis   (ICI)
    global top-k with (score desc, shard asc, doc asc)    (sortDocs)
    lax.psum / pmin / pmax of aggregation bucket arrays   (agg reduce)

The query batch additionally splits over the "replica" axis (data
parallelism over requests). The same eval_node/eval_aggs interpreters
used by the single-chip executor run inside shard_map — one code path,
two placements.

Packing: every logical shard is force-merged to one columnar segment,
padded to COMMON shapes (cap, posting-block count), with keyword
ordinals remapped into a MESH-GLOBAL ordinal space at pack time so
bucket arrays reduce exactly across shards.
"""

from __future__ import annotations

import json
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.8
    from jax import shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map

from ..index.mapping import MapperService
from ..index.segment import Segment, SegmentBuilder, next_pow2, merge_segments, BLOCK
from ..search.executor import QueryBinder, finalize, eval_node, eval_aggs
from ..search.query_dsl import QueryParser
from ..search.aggregations import (parse_aggs, ShardAggContext, AggSpec,
                                   merge_shard_partials, finalize_partials,
                                   shard_partials)
from ..ops.topk import top_k_hits
from ..utils.errors import SearchParseError


class _UnionShardView:
    """Binding view of one shard exposing the UNION of all shards' fields
    (missing ones as empty stubs) so one query binds to ONE plan shape on
    every shard — per-shard structural differences (absent field, dense
    vs scatter) must not fork the compiled program."""

    def __init__(self, seg: Segment, text: dict, keywords: dict, numerics: dict):
        self._seg = seg
        self.text = text
        self.keywords = keywords
        self.numerics = numerics

    def __getattr__(self, name):
        return getattr(self._seg, name)

    def field_kind(self, name: str) -> str | None:
        if name in self.text:
            return "text"
        if name in self.keywords:
            return "keyword"
        if name in self.numerics:
            return "numeric"
        return None


class PackedShards:
    """Host + device representation of S shards with aligned shapes."""

    def __init__(self, index_name: str, shards: list[Segment],
                 mapper: MapperService, mesh: Mesh):
        self.index_name = index_name
        self.mappers = mapper
        self.mesh = mesh
        self.n_shards = mesh.shape["shard"]
        if len(shards) != self.n_shards:
            raise ValueError(f"packed {len(shards)} shards for a "
                             f"{self.n_shards}-shard mesh")
        self.shards = shards
        self.cap = max(next_pow2(max(s.capacity for s in shards), floor=BLOCK),
                       BLOCK)
        # a field is dense-capable only if EVERY shard has its forward
        # index (mixed plans would fork the program shape)
        self.fwd_disabled = {
            f for s in shards for f, pf in s.text.items()
            if pf.fwd_tids is None}

        # mesh-global keyword ordinal spaces
        self.kw_terms: dict[str, list[str]] = {}
        kw_fields = sorted({f for s in shards for f in s.keywords})
        for f in kw_fields:
            self.kw_terms[f] = sorted(
                {t for s in shards if f in s.keywords
                 for t in s.keywords[f].terms})

        text_fields = sorted({f for s in shards for f in s.text})
        num_fields = sorted({f for s in shards for f in s.numerics})

        S, cap = self.n_shards, self.cap
        arrays: dict = {"text": {}, "kw": {}, "num": {}}
        for f in text_fields:
            dense = f not in self.fwd_disabled
            nb = max(next_pow2(max(
                (s.text[f].block_docs.shape[0] if f in s.text else 1)
                for s in shards), floor=1), 1)
            docs = np.full((S, nb, BLOCK), cap, dtype=np.int32)
            imps = np.zeros((S, nb, BLOCK), dtype=np.float32)
            dlen = np.zeros((S, cap), dtype=np.float32)
            entry = {"block_docs": docs, "block_imps": imps, "doc_len": dlen}
            if dense:
                fwd_l = max(next_pow2(max(
                    (s.text[f].fwd_tids.shape[1] if f in s.text else 8)
                    for s in shards), floor=8), 8)
                ftids = np.full((S, cap, fwd_l), -1, dtype=np.int32)
                fimps = np.zeros((S, cap, fwd_l), dtype=np.float32)
                entry["fwd_tids"] = ftids
                entry["fwd_imps"] = fimps
            for i, s in enumerate(shards):
                pf = s.text.get(f)
                if pf is None:
                    continue
                bd = pf.block_docs
                docs[i, : bd.shape[0]] = np.where(bd >= s.capacity, cap, bd)
                imps[i, : bd.shape[0]] = pf.block_imps
                dlen[i, : s.capacity] = pf.doc_len
                if dense:
                    ftids[i, : s.capacity, : pf.fwd_tids.shape[1]] = pf.fwd_tids
                    fimps[i, : s.capacity, : pf.fwd_imps.shape[1]] = pf.fwd_imps
            arrays["text"][f] = entry
        for f in kw_fields:
            lookup = {t: i for i, t in enumerate(self.kw_terms[f])}
            ords = np.full((S, cap), -1, dtype=np.int32)
            for i, s in enumerate(shards):
                kc = s.keywords.get(f)
                if kc is None:
                    continue
                remap = np.asarray([lookup[t] for t in kc.terms],
                                   dtype=np.int32)
                local = kc.ords[: s.capacity]
                if remap.size:
                    ords[i, : s.capacity] = np.where(
                        local >= 0, remap[np.clip(local, 0, None)], -1)
            arrays["kw"][f] = ords
            # multi-valued sidecar: remapped ord sets (same branch the
            # single-chip interpreter takes via seg["kw_mv"])
            M = max((s.keywords[f].mv_ords.shape[1]
                     for s in shards
                     if f in s.keywords
                     and s.keywords[f].mv_ords is not None), default=0)
            if M:
                mv = np.full((S, cap, M), -1, dtype=np.int32)
                for i, s in enumerate(shards):
                    kc = s.keywords.get(f)
                    if kc is None:
                        continue
                    remap = np.asarray([lookup[t] for t in kc.terms],
                                       dtype=np.int32)
                    if kc.mv_ords is not None:
                        local = kc.mv_ords[: s.capacity]
                        mv[i, : s.capacity, : local.shape[1]] = np.where(
                            local >= 0, remap[np.clip(local, 0, None)], -1)
                    else:
                        local = kc.ords[: s.capacity]
                        mv[i, : s.capacity, 0] = np.where(
                            local >= 0, remap[np.clip(local, 0, None)], -1)
                arrays.setdefault("kw_mv", {})[f] = mv
        for f in num_fields:
            kinds = {s.numerics[f].values.dtype.type
                     for s in shards if f in s.numerics}
            dtype = np.float32 if np.float32 in kinds else np.int32
            vals = np.zeros((S, cap), dtype=dtype)
            exists = np.zeros((S, cap), dtype=bool)
            for i, s in enumerate(shards):
                nc = s.numerics.get(f)
                if nc is None:
                    continue
                vals[i, : s.capacity] = nc.values.astype(dtype)
                exists[i, : s.capacity] = nc.exists
            entry = {"values": vals, "exists": exists}
            M = max((s.numerics[f].mv_values.shape[1]
                     for s in shards
                     if f in s.numerics
                     and s.numerics[f].mv_values is not None), default=0)
            if M:
                mvv = np.zeros((S, cap, M), dtype=dtype)
                mve = np.zeros((S, cap, M), dtype=bool)
                for i, s in enumerate(shards):
                    nc = s.numerics.get(f)
                    if nc is None:
                        continue
                    if nc.mv_values is not None:
                        w = nc.mv_values.shape[1]
                        mvv[i, : s.capacity, :w] = \
                            nc.mv_values[: s.capacity].astype(dtype)
                        mve[i, : s.capacity, :w] = \
                            nc.mv_exists[: s.capacity]
                    else:
                        mvv[i, : s.capacity, 0] = nc.values.astype(dtype)
                        mve[i, : s.capacity, 0] = nc.exists
                entry["mv_values"] = mvv
                entry["mv_exists"] = mve
            arrays["num"][f] = entry
        live = np.zeros((S, cap), dtype=bool)
        for i, s in enumerate(shards):
            live[i, : s.num_docs] = True

        def shard_put(a: np.ndarray):
            spec = P("shard", *([None] * (a.ndim - 1)))
            return jax.device_put(jnp.asarray(a), NamedSharding(mesh, spec))

        num_dtypes = {f: arrays["num"][f]["values"].dtype for f in num_fields}
        self.dev = jax.tree_util.tree_map(shard_put, arrays)
        self.live = shard_put(live)

        # per-shard union binding views (one plan shape for all shards)
        from ..index.segment import PostingsField, KeywordColumn, NumericColumn
        import copy as _copy

        self.bind_views: list[_UnionShardView] = []
        for s in shards:
            text = {}
            for f in text_fields:
                pf = s.text.get(f)
                if pf is None:
                    pf = PostingsField(
                        name=f, terms=[], term_index={},
                        df=np.zeros(0, np.int32), indptr=np.zeros(1, np.int64),
                        doc_ids=np.zeros(0, np.int32),
                        tfs=np.zeros(0, np.float32),
                        doc_len=np.zeros(s.capacity, np.float32),
                        doc_count=0, avg_len=1.0)
                    pf.block_start = np.zeros(1, np.int32)
                    pf.fwd_tids = (None if f in self.fwd_disabled
                                   else np.zeros((0, 0), np.int32))
                elif f in self.fwd_disabled and pf.fwd_tids is not None:
                    pf = _copy.copy(pf)
                    pf.fwd_tids = None
                    pf.fwd_imps = None
                text[f] = pf
            kws = {}
            for f in kw_fields:
                kc = s.keywords.get(f)
                if kc is None:
                    kc = KeywordColumn(name=f, terms=[], term_index={},
                                       ords=np.full(0, -1, np.int32),
                                       df=np.zeros(0, np.int32))
                kws[f] = kc
            nums = {}
            for f in num_fields:
                kind = next(s2.numerics[f].kind for s2 in shards
                            if f in s2.numerics)
                bias = next(s2.numerics[f].bias for s2 in shards
                            if f in s2.numerics)
                # dtype-signaling stub: range/term binds must pick the
                # PACK dtype on every shard, not the local column's
                nums[f] = NumericColumn(
                    name=f, kind=kind, values=np.zeros(0, num_dtypes[f]),
                    exists=np.zeros(0, bool), raw=np.zeros(0, np.int64),
                    bias=bias)
            self.bind_views.append(_UnionShardView(s, text, kws, nums))

    @classmethod
    def from_node_index(cls, node, index_name: str, mesh: Mesh) -> "PackedShards":
        """Pack a Node's index (force-merging each shard to one segment)."""
        svc = node.indices[index_name]
        shards = []
        for sid in range(svc.num_shards):
            eng = svc.shard(sid)
            eng.refresh()
            if len(eng.segments) == 0:
                shards.append(SegmentBuilder().build(f"empty_{sid}"))
            else:
                # always a fresh copy: PackedShards owns its segments (it
                # may normalize forward-index availability across shards)
                shards.append(merge_segments(eng.segments, f"packed_{sid}",
                                             eng.live))
        return cls(index_name, shards, svc.mappers, mesh)


def _reduce_shard_axis(agg_out: dict) -> dict:
    """psum counts/sums, pmin mins, pmax maxes over the shard axis."""
    def walk(obj):
        if isinstance(obj, dict):
            out = {}
            for key, v in obj.items():
                if isinstance(v, dict):
                    out[key] = walk(v)
                elif key == "min":
                    out[key] = jax.lax.pmin(v, "shard")
                elif key == "max":
                    out[key] = jax.lax.pmax(v, "shard")
                else:
                    out[key] = jax.lax.psum(v, "shard")
            return out
        return jax.lax.psum(obj, "shard")

    return walk(agg_out)


class DistributedSearcher:
    """Executes searches as one shard_map program over the mesh."""

    def __init__(self, packed: PackedShards):
        self.packed = packed
        self.mesh = packed.mesh
        self.n_replicas = self.mesh.shape["replica"]
        self._jit_cache: dict = {}

    # -- public ------------------------------------------------------------
    def search(self, body: dict) -> dict:
        return self.msearch([body])[0]

    def msearch(self, bodies: list[dict]) -> list[dict]:
        """All bodies must share one plan structure (they batch over the
        replica axis) and the first body's aggs apply to the batch."""
        pk = self.packed
        n = len(bodies)
        parser = QueryParser(pk.mappers)
        queries = [parser.parse(b.get("query")) for b in bodies]
        sizes = [int(b.get("size", 10)) + int(b.get("from", 0)) for b in bodies]
        k = min(next_pow2(max(max(sizes), 1), floor=1), pk.cap)
        agg_specs = parse_aggs(bodies[0].get("aggs")
                               or bodies[0].get("aggregations"))
        for spec in agg_specs:
            fm = pk.mappers.field(spec.field)
            if spec.kind in ("terms", "cardinality", "value_count") and \
                    fm is not None and fm.type == "text" and \
                    pk.mappers.field(f"{spec.field}.keyword") is not None:
                spec.field = f"{spec.field}.keyword"

        # pad batch to a replica-axis multiple
        R = self.n_replicas
        B = ((max(n, 1) + R - 1) // R) * R
        queries = queries + [queries[0]] * (B - n)

        # bind per (shard, query) against the UNION views; ONE finalize
        # over the flattened batch guarantees identical desc across shards
        flat_bounds = []
        for view in pk.bind_views:
            binder = QueryBinder(view, pk.mappers)  # type: ignore[arg-type]
            flat_bounds.extend(binder.bind(q) for q in queries)
        sig0 = flat_bounds[0].signature()
        for bnd in flat_bounds[1:]:
            if bnd.signature() != sig0:
                raise SearchParseError(
                    "distributed msearch requires structurally identical "
                    "queries (split heterogeneous batches)")
        desc, flat_params = finalize(flat_bounds)      # leaves [S*B, ...]
        params = jax.tree_util.tree_map(
            lambda a: a.reshape(pk.n_shards, B, *a.shape[1:]), flat_params)

        agg_desc, agg_params = self._build_aggs(agg_specs)
        run = self._compiled(desc, agg_desc, k)
        (m_score, m_shard, m_doc, total), agg_out = jax.device_get(
            run(pk.dev, pk.live, params, agg_params))

        per_query_partials = None
        if agg_specs:
            per_query_partials = shard_partials(
                agg_specs, self._agg_ctx,
                [jax.tree_util.tree_map(np.asarray, agg_out)], batch=B)
        responses = []
        for i, body in enumerate(bodies):
            frm = int(body.get("from", 0))
            size = int(body.get("size", 10))
            nvalid = int(min(total[i], m_score.shape[1]))
            hits = []
            for j in range(frm, min(frm + size, nvalid)):
                s = int(m_shard[i, j])
                d = int(m_doc[i, j])
                seg = pk.shards[s]
                hits.append({
                    "_index": pk.index_name,
                    "_type": "_doc",
                    "_id": seg.ids[d],
                    "_score": float(m_score[i, j]),
                    "_source": json.loads(seg.sources[d]),
                })
            resp = {
                "took": 0, "timed_out": False,
                "_shards": {"total": pk.n_shards,
                            "successful": pk.n_shards, "failed": 0},
                "hits": {"total": int(total[i]),
                         "max_score": float(m_score[i, 0]) if nvalid else None,
                         "hits": hits},
            }
            if agg_specs:
                merged = merge_shard_partials(agg_specs,
                                              [per_query_partials[i]])
                resp["aggregations"] = finalize_partials(agg_specs, merged)
            responses.append(resp)
        return responses

    # -- aggs --------------------------------------------------------------
    def _build_aggs(self, specs: list[AggSpec]):
        pk = self.packed
        self._agg_ctx = None
        if not specs:
            return (), ()
        global_ords = {}
        for s in specs:
            if s.kind in ("terms", "cardinality"):
                terms = pk.kw_terms.get(s.field, [])
                ident = np.arange(max(len(terms), 1), dtype=np.int32)
                # identity maps: packed columns already hold mesh-global ords
                global_ords[s.field] = (terms, [ident] * pk.n_shards)
        self._agg_ctx = ShardAggContext(pk.shards, global_ords)
        agg_desc, per_seg = self._agg_ctx.build(specs)
        if not per_seg:
            return agg_desc, ()
        stacked = jax.tree_util.tree_map(lambda *xs: np.stack(xs), *per_seg)
        return agg_desc, stacked

    # -- the distributed program ------------------------------------------
    def _compiled(self, desc, agg_desc, k: int):
        key = (desc, agg_desc, k)
        fn = self._jit_cache.get(key)
        if fn is not None:
            return fn
        pk = self.packed
        mesh = self.mesh
        cap = pk.cap

        @partial(shard_map, mesh=mesh,
                 in_specs=(P("shard"), P("shard"), P("shard", "replica"),
                           P("shard")),
                 out_specs=((P("replica"), P("replica"), P("replica"),
                             P("replica")), P("replica")),
                 check_vma=False)
        def program(seg, live, prm, agg_prm):
            seg = jax.tree_util.tree_map(lambda a: a[0], seg)
            live_l = live[0]
            prm_l = jax.tree_util.tree_map(lambda a: a[0], prm)
            agg_l = jax.tree_util.tree_map(lambda a: a[0], agg_prm)
            leaves = jax.tree_util.tree_leaves(prm_l)
            b_loc = leaves[0].shape[0] if leaves else 1

            score, match = eval_node(desc, prm_l, seg, cap, b_loc)
            valid = match & live_l[None, :]
            score = jnp.where(valid, score, 0.0)
            l_score, l_idx, l_total = top_k_hits(score, valid, min(k, cap))

            # ---- cross-shard reduce over ICI (SearchPhaseController) ----
            g_score = jax.lax.all_gather(l_score, "shard")   # [S, b, k]
            g_idx = jax.lax.all_gather(l_idx, "shard")
            S = g_score.shape[0]
            kk = l_score.shape[1]
            # shard-major flatten => top_k tie-break = (shard asc, rank asc)
            flat_score = jnp.moveaxis(g_score, 0, 1).reshape(b_loc, S * kk)
            flat_idx = jnp.moveaxis(g_idx, 0, 1).reshape(b_loc, S * kk)
            shard_of = jnp.repeat(jnp.arange(S, dtype=jnp.int32), kk)[None, :]
            m_score, m_pos = jax.lax.top_k(flat_score, kk)
            m_shard = jnp.take_along_axis(
                jnp.broadcast_to(shard_of, flat_idx.shape), m_pos, axis=1)
            m_doc = jnp.take_along_axis(flat_idx, m_pos, axis=1)
            total = jax.lax.psum(l_total, "shard")

            agg_out = eval_aggs(agg_desc, agg_l, seg, valid)
            agg_out = _reduce_shard_axis(agg_out)
            return (m_score, m_shard, m_doc, total), agg_out

        fn = jax.jit(program)
        self._jit_cache[key] = fn
        return fn
