"""Mesh-distributed search: shard-parallel scoring with in-program reduce.

Reference analog: the distributed QUERY phase — TransportSearchAction
fanning out to one copy of every shard (TransportSearchTypeAction.java:
126-153) and SearchPhaseController merging shard top-k + agg trees on a
coordinating node (SearchPhaseController.java:147-282).

TPU-first redesign: instead of RPC fan-out + host merge, the WHOLE
distributed query is ONE jitted program over a ("replica", "shard")
mesh via shard_map:

    each device scores ITS shard's columns locally        (QueryPhase)
    lax.all_gather of local top-k over the "shard" axis   (ICI)
    global top-k with (score desc, shard asc, doc asc)    (sortDocs)
    lax.psum / pmin / pmax of aggregation bucket arrays   (agg reduce)

The query batch additionally splits over the "replica" axis (data
parallelism over requests). The same eval_node/eval_aggs interpreters
used by the single-chip executor run inside shard_map — one code path,
two placements.

Packing: every logical shard is force-merged to one columnar segment,
padded to COMMON shapes (cap, posting-block count), with keyword
ordinals remapped into a MESH-GLOBAL ordinal space at pack time so
bucket arrays reduce exactly across shards.
"""

from __future__ import annotations

import json
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.8
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as _shard_map

import inspect as _inspect

if "check_vma" in _inspect.signature(_shard_map).parameters:
    shard_map = _shard_map
else:
    # older jax spells the replication check `check_rep`; translate so
    # the call sites stay on the current-jax spelling
    def shard_map(f=None, **kw):
        kw["check_rep"] = kw.pop("check_vma", True)
        return _shard_map(f, **kw) if f is not None else \
            (lambda g: _shard_map(g, **kw))

from ..index.mapping import MapperService
from ..index.segment import (Segment, SegmentBuilder, next_pow2,
                             merge_segments, pad_delta_shapes, BLOCK,
                             build_tile_max, build_tile_minmax,
                             score_tile_size)
from ..search.executor import (QueryBinder, finalize, eval_node,
                               eval_aggs, _agg_view_plan, _ViewMasks,
                               _bound_view_fields, _fused_plan_bundle,
                               _fused_params_ok, _bundle_pallas_reason,
                               _bundle_pos_width, _bundle_positional,
                               _FUSED_DENSE_KINDS, _FUSED_RANGE_KINDS,
                               _FUSED_VEC_KINDS,
                               eval_fused_topk, resolve_fused_backend,
                               autotune_persist_key, seg_cache_key,
                               _fused_stats,
                               _resident_step, _split_deadline,
                               _RESIDENT_CHUNKS)
from ..search.query_dsl import QueryParser
from ..search.aggregations import (parse_aggs, ShardAggContext, AggSpec,
                                   merge_shard_partials, finalize_partials,
                                   shard_partials)
from ..ops.topk import top_k_hits
from ..search.controller import shards_header
from ..utils.errors import (QueryParsingError, SearchParseError,
                            SearchTimeoutError)

# request-shaped errors: every replica row would reject them the same
# way, so they never retry, never count toward device health, and
# surface unchanged
_PARSE_ERRORS = (SearchParseError, QueryParsingError)


def _mesh_stepped_enabled() -> bool:
    """May a deadline-carrying mesh search run the STEPPED program form
    (the preemptive device-side timeout the single-chip resident loop
    already has)? The stepped form chunks the fused tile walk and polls
    the host clock between chunks via io_callback — callbacks inside
    shard_map are per-device host calls with NO collectives in the
    chunk loop, so devices may disagree transiently on the verdict
    without desyncing; the final verdict is psum'd over BOTH mesh axes,
    making the timeout decision collective. Multi-process meshes stay
    cooperative: each process would poll its OWN monotonic clock
    against a deadline minted on the coordinator's, which is
    meaningless cross-host."""
    import os
    if os.environ.get("ES_TPU_MESH_STEPPED", "auto").lower() in (
            "0", "false", "off"):
        return False
    try:
        return jax.process_count() == 1
    except Exception:  # pragma: no cover - uninitialized backend
        return False


class _UnionShardView:
    """Binding view of one shard exposing the UNION of all shards' fields
    (missing ones as empty stubs) so one query binds to ONE plan shape on
    every shard — per-shard structural differences (absent field, dense
    vs scatter) must not fork the compiled program."""

    def __init__(self, seg: Segment, text: dict, keywords: dict,
                 numerics: dict, num_docs: int | None = None,
                 vectors: dict | None = None):
        self._seg = seg
        self.text = text
        self.keywords = keywords
        self.numerics = numerics
        # vector stubs carry the pack dims so a knn clause binds to ONE
        # desc on every shard (a shard without the column still binds
        # knn_vec; its packed rows have exists=False everywhere)
        if vectors is not None:
            self.vectors = vectors
        # keyword idf binds against the GLOBAL df the view carries, so
        # the doc count must be mesh-global too (else df > num_docs on
        # a small shard flips idf negative)
        if num_docs is not None:
            self.num_docs = num_docs

    def __getattr__(self, name):
        return getattr(self._seg, name)

    def field_kind(self, name: str) -> str | None:
        if name in self.text:
            return "text"
        if name in self.keywords:
            return "keyword"
        if name in self.numerics:
            return "numeric"
        if name in getattr(self, "vectors", {}):
            return "vector"
        return None


def summarize_shards(shards: list[Segment]) -> dict:
    """JSON-able pack summary of a host's LOCAL shards — the control-
    plane message from which every host derives the IDENTICAL global
    pack spec (merge_summaries). Multi-host packing exchanges these
    over the cluster transport instead of shipping shard data."""
    text = {}
    for f in sorted({f for s in shards for f in s.text}):
        nb = max((s.text[f].block_docs.shape[0] if f in s.text else 1)
                 for s in shards)
        fwd_ok = all(s.text[f].fwd_tids is not None
                     for s in shards if f in s.text)
        fwd_l = max((s.text[f].fwd_tids.shape[1]
                     for s in shards
                     if f in s.text and s.text[f].fwd_tids is not None),
                    default=8)
        # positional sidecar: packable only when EVERY shard carries it
        # (a mixed pack would fork the SPMD program); pos_p is the
        # per-slot position capacity the mesh slab pads to
        pos_ok = fwd_ok and all(
            getattr(s.text[f], "fwd_pos", None) is not None
            for s in shards if f in s.text)
        pos_p = max((s.text[f].fwd_pos.shape[1]
                     // s.text[f].fwd_tids.shape[1]
                     for s in shards
                     if f in s.text
                     and getattr(s.text[f], "fwd_pos", None) is not None),
                    default=0)
        # term-dictionary width: sizes the mesh-global tile_max pad so
        # every host packs identically-shaped block-max summaries
        nt = max((len(s.text[f].terms) for s in shards if f in s.text),
                 default=0)
        text[f] = {"nb": int(nb), "fwd_ok": bool(fwd_ok),
                   "fwd_l": int(fwd_l), "nt": int(nt),
                   "pos_ok": bool(pos_ok), "pos_p": int(pos_p)}
    kw = {}
    for f in sorted({f for s in shards for f in s.keywords}):
        df: dict[str, int] = {}
        for s in shards:
            kc = s.keywords.get(f)
            if kc is None:
                continue
            for t, d in zip(kc.terms, kc.df):
                df[t] = df.get(t, 0) + int(d)
        mv = max((s.keywords[f].mv_ords.shape[1]
                  for s in shards
                  if f in s.keywords
                  and s.keywords[f].mv_ords is not None), default=0)
        kw[f] = {"df": df, "mv": int(mv)}
    num = {}
    for f in sorted({f for s in shards for f in s.numerics}):
        any_f32 = any(s.numerics[f].values.dtype == np.float32
                      for s in shards if f in s.numerics)
        mv = max((s.numerics[f].mv_values.shape[1]
                  for s in shards
                  if f in s.numerics
                  and s.numerics[f].mv_values is not None), default=0)
        nc0 = next(s.numerics[f] for s in shards if f in s.numerics)
        lo = hi = None
        for s in shards:
            nc = s.numerics.get(f)
            if nc is None:
                continue
            vals = (nc.mv_values[nc.mv_exists] if nc.mv_values is not None
                    else nc.values[: s.capacity][nc.exists])
            if vals.size:
                lo = float(vals.min()) if lo is None else min(
                    lo, float(vals.min()))
                hi = float(vals.max()) if hi is None else max(
                    hi, float(vals.max()))
        num[f] = {"f32": bool(any_f32), "mv": int(mv),
                  "kind": nc0.kind, "bias": int(nc0.bias),
                  "lo": lo, "hi": hi}
    vec = {}
    for f in sorted({f for s in shards for f in s.vectors}):
        dims = max(s.vectors[f].dims for s in shards if f in s.vectors)
        vec[f] = {"dims": int(dims)}
    return {"cap": int(max((s.capacity for s in shards), default=BLOCK)),
            "total_docs": int(sum(s.num_docs for s in shards)),
            "text": text, "kw": kw, "num": num, "vec": vec}


class PackSpec:
    """The global shape contract every host packs to. Deterministic
    function of the merged summaries, so independently-merging hosts
    agree bit-for-bit."""

    def __init__(self, summaries: list[dict], n_shards: int):
        self.n_shards = n_shards
        self.cap = max(next_pow2(
            max(s["cap"] for s in summaries), floor=BLOCK), BLOCK)
        self.total_docs = sum(s["total_docs"] for s in summaries)
        text_fields = sorted({f for s in summaries for f in s["text"]})
        self.text: dict[str, dict] = {}
        self.fwd_disabled: set[str] = set()
        for f in text_fields:
            entries = [s["text"][f] for s in summaries if f in s["text"]]
            if not all(e["fwd_ok"] for e in entries):
                self.fwd_disabled.add(f)
            # nt=0 (any summary from a peer without the field, or a
            # pre-tile_max summary) disables block-max packing for the
            # field rather than desyncing hosts on the summary shape
            nts = [e.get("nt", 0) for e in entries]
            # positions pack only when every host's shards carry the
            # sidecar (pos_ok everywhere, width agreed by pow2 pad);
            # absent/mixed summaries disable it rather than desync
            pps = [e.get("pos_p", 0) for e in entries]
            self.text[f] = {
                "nb": max(next_pow2(max(e["nb"] for e in entries),
                                    floor=1), 1),
                "fwd_l": max(next_pow2(max(e["fwd_l"] for e in entries),
                                       floor=8), 8),
                "nt": (next_pow2(max(nts), floor=1)
                       if all(n > 0 for n in nts) else 0),
                "pos_p": (next_pow2(max(pps), floor=1)
                          if all(e.get("pos_ok") for e in entries)
                          and all(p > 0 for p in pps) else 0)}
        self.kw_terms: dict[str, list[str]] = {}
        self.kw_df: dict[str, np.ndarray] = {}
        self.kw_mv: dict[str, int] = {}
        for f in sorted({f for s in summaries for f in s["kw"]}):
            df: dict[str, int] = {}
            mv = 0
            for s in summaries:
                e = s["kw"].get(f)
                if e is None:
                    continue
                mv = max(mv, e["mv"])
                for t, d in e["df"].items():
                    df[t] = df.get(t, 0) + d
            terms = sorted(df)
            self.kw_terms[f] = terms
            self.kw_df[f] = np.asarray([df[t] for t in terms],
                                       dtype=np.int32)
            self.kw_mv[f] = mv
        # dense_vector fields (mapping-fixed dims, so every summary
        # agrees; max is belt-and-braces against partial mappings)
        self.vec: dict[str, dict] = {}
        for f in sorted({f for s in summaries for f in s.get("vec", {})}):
            self.vec[f] = {"dims": max(s["vec"][f]["dims"]
                                       for s in summaries
                                       if f in s.get("vec", {}))}
        self.num: dict[str, dict] = {}
        for f in sorted({f for s in summaries for f in s["num"]}):
            entries = [s["num"][f] for s in summaries if f in s["num"]]
            los = [e["lo"] for e in entries if e.get("lo") is not None]
            his = [e["hi"] for e in entries if e.get("hi") is not None]
            self.num[f] = {
                "dtype": (np.float32 if any(e["f32"] for e in entries)
                          else np.int32),
                "mv": max(e["mv"] for e in entries),
                "kind": entries[0]["kind"],
                "bias": entries[0]["bias"],
                # MESH-GLOBAL extent: histogram origins / bucket counts
                # are static program shape, so every host must derive
                # them from the same numbers
                "ext": ((min(los), max(his)) if los else None)}


class PackedShards:
    """Host + device representation of S shards with aligned shapes.

    `spec`/`shard_offset`/`placer` support multi-host packing: each
    host packs only its LOCAL shards against the GLOBAL PackSpec and
    places rows into the global mesh array via its own placer
    (parallel/multihost.py); single-host callers omit all three."""

    def __init__(self, index_name: str, shards: list[Segment],
                 mapper: MapperService, mesh: Mesh,
                 spec: PackSpec | None = None, shard_offset: int = 0,
                 placer=None):
        self.index_name = index_name
        self.mappers = mapper
        self.mesh = mesh
        self.n_shards = mesh.shape["shard"]
        if spec is None:
            spec = PackSpec([summarize_shards(shards)], self.n_shards)
        if spec.n_shards != self.n_shards:
            raise ValueError(f"spec for {spec.n_shards} shards on a "
                             f"{self.n_shards}-shard mesh")
        if shard_offset + len(shards) > self.n_shards:
            raise ValueError(f"packed rows {shard_offset}+{len(shards)} "
                             f"exceed the {self.n_shards}-shard mesh")
        self.spec = spec
        self.shard_offset = shard_offset
        self.shards = shards
        self.cap = spec.cap
        # tiered tile residency (index/tiering.py): the mesh pack is
        # ONE SPMD array set over all rows, so per-row tile paging
        # would fork the shard_map program per residency state — mesh
        # rows stay fully resident for now (single-chip packs page).
        # Rows whose pack exceeds the tiering budget are COUNTED so an
        # oversubscribed mesh is observable in the stats instead of
        # silently un-tiered; their summaries still register with the
        # pager's stats surface through the per-segment stores.
        from ..index import tiering as _tiering
        if _tiering.enabled():
            budget = _tiering.budget_bytes()
            for s in shards:
                fwd_bytes = sum(
                    pf.fwd_tids.nbytes + pf.fwd_imps.nbytes
                    for pf in s.text.values()
                    if pf.fwd_tids is not None)
                if s.nbytes() + fwd_bytes > budget:
                    _tiering.stats.mesh_full_resident_rows.inc()
        # a field is dense-capable only if EVERY shard (on every host)
        # has its forward index (mixed plans would fork the program)
        self.fwd_disabled = spec.fwd_disabled

        # mesh-global keyword ordinal spaces
        self.kw_terms = spec.kw_terms
        kw_fields = sorted(spec.kw_terms)
        text_fields = sorted(spec.text)
        num_fields = sorted(spec.num)

        S, cap = len(shards), self.cap
        arrays: dict = {"text": {}, "kw": {}, "num": {}}
        for f in text_fields:
            dense = f not in self.fwd_disabled
            nb = spec.text[f]["nb"]
            docs = np.full((S, nb, BLOCK), cap, dtype=np.int32)
            imps = np.zeros((S, nb, BLOCK), dtype=np.float32)
            dlen = np.zeros((S, cap), dtype=np.float32)
            entry = {"block_docs": docs, "block_imps": imps, "doc_len": dlen}
            pos_p = spec.text[f].get("pos_p", 0) if dense else 0
            if dense:
                fwd_l = spec.text[f]["fwd_l"]
                ftids = np.full((S, cap, fwd_l), -1, dtype=np.int32)
                fimps = np.zeros((S, cap, fwd_l), dtype=np.float32)
                entry["fwd_tids"] = ftids
                entry["fwd_imps"] = fimps
                if pos_p:
                    # positional slab rides the mesh pack next to the
                    # forward pair: [S, cap, fwd_l, P] padded with the
                    # -1 empty-delta sentinel, flattened to the same
                    # [*, L*P] slot layout the single-chip decode reads
                    fpos = np.full((S, cap, fwd_l, pos_p), -1,
                                   dtype=np.int16)
                    fk1ln = np.ones((S, cap), dtype=np.float32)
                    flnorm = np.ones((S, cap), dtype=np.float32)
            for i, s in enumerate(shards):
                pf = s.text.get(f)
                if pf is None:
                    continue
                bd = pf.block_docs
                docs[i, : bd.shape[0]] = np.where(bd >= s.capacity, cap, bd)
                imps[i, : bd.shape[0]] = pf.block_imps
                dlen[i, : s.capacity] = pf.doc_len
                if dense:
                    ftids[i, : s.capacity, : pf.fwd_tids.shape[1]] = pf.fwd_tids
                    fimps[i, : s.capacity, : pf.fwd_imps.shape[1]] = pf.fwd_imps
                    if pos_p:
                        l_s = pf.fwd_tids.shape[1]
                        p_s = pf.fwd_pos.shape[1] // l_s
                        fpos[i, : s.capacity, : l_s, : p_s] = \
                            pf.fwd_pos.reshape(s.capacity, l_s, p_s)
                        fk1ln[i, : s.capacity] = pf.k1ln
                        flnorm[i, : s.capacity] = pf.lnorm
            if dense and pos_p:
                entry["fwd_pos"] = fpos.reshape(S, cap, fwd_l * pos_p)
                entry["k1ln"] = fk1ln
                entry["lnorm"] = flnorm
            if dense and spec.text[f].get("nt", 0) > 0:
                # per-shard-row block-max summaries over the PACKED
                # forward index (shard-local term ids, mesh-common tile
                # grid) — what routes the shard_map program through the
                # fused score+top-k op. Term rows pad with zero impact:
                # absent terms bound to 0 and can never un-prune a tile.
                nt = spec.text[f]["nt"]
                tms = []
                for i in range(S):
                    tm = build_tile_max(ftids[i], fimps[i], nt, cap,
                                        tile=score_tile_size(cap))
                    if tm is None:
                        tms = None
                        break
                    tms.append(tm)
                if tms is not None:
                    entry["tile_max"] = np.stack(tms)
            arrays["text"][f] = entry
        for f in kw_fields:
            lookup = {t: i for i, t in enumerate(self.kw_terms[f])}
            ords = np.full((S, cap), -1, dtype=np.int32)
            for i, s in enumerate(shards):
                kc = s.keywords.get(f)
                if kc is None:
                    continue
                remap = np.asarray([lookup[t] for t in kc.terms],
                                   dtype=np.int32)
                local = kc.ords[: s.capacity]
                if remap.size:
                    ords[i, : s.capacity] = np.where(
                        local >= 0, remap[np.clip(local, 0, None)], -1)
            arrays["kw"][f] = ords
            # multi-valued sidecar: remapped ord sets (same branch the
            # single-chip interpreter takes via seg["kw_mv"])
            M = spec.kw_mv[f]
            if M:
                mv = np.full((S, cap, M), -1, dtype=np.int32)
                for i, s in enumerate(shards):
                    kc = s.keywords.get(f)
                    if kc is None:
                        continue
                    remap = np.asarray([lookup[t] for t in kc.terms],
                                       dtype=np.int32)
                    if kc.mv_ords is not None:
                        local = kc.mv_ords[: s.capacity]
                        mv[i, : s.capacity, : local.shape[1]] = np.where(
                            local >= 0, remap[np.clip(local, 0, None)], -1)
                    else:
                        local = kc.ords[: s.capacity]
                        mv[i, : s.capacity, 0] = np.where(
                            local >= 0, remap[np.clip(local, 0, None)], -1)
                arrays.setdefault("kw_mv", {})[f] = mv
        # dense_vector columns, one [S, cap, D] slab per field: vectors
        # shard across the mesh shard axis exactly like postings do (a
        # shard row carries its own docs' vectors), so the PR 4/7/13
        # failover / eviction-repack / host-elasticity arcs cover
        # vector serving with no extra machinery. Host packs f32; the
        # similarity matmul casts to bf16 at eval, same math as the
        # single-chip column (ops/knn.knn_score_column).
        vec_fields = sorted(spec.vec)
        for f in vec_fields:
            D = spec.vec[f]["dims"]
            vvals = np.zeros((S, cap, D), dtype=np.float32)
            vexists = np.zeros((S, cap), dtype=bool)
            vnorms = np.zeros((S, cap), dtype=np.float32)
            for i, s in enumerate(shards):
                vc = s.vectors.get(f)
                if vc is None:
                    continue
                vvals[i, : s.capacity, : vc.dims] = vc.values
                vexists[i, : s.capacity] = vc.exists
                vnorms[i, : s.capacity] = vc.norms
            arrays.setdefault("vec", {})[f] = {
                "values": vvals, "exists": vexists, "norms": vnorms}
        for f in num_fields:
            dtype = spec.num[f]["dtype"]
            vals = np.zeros((S, cap), dtype=dtype)
            exists = np.zeros((S, cap), dtype=bool)
            for i, s in enumerate(shards):
                nc = s.numerics.get(f)
                if nc is None:
                    continue
                vals[i, : s.capacity] = nc.values.astype(dtype)
                exists[i, : s.capacity] = nc.exists
            entry = {"values": vals, "exists": exists}
            if not spec.num[f]["mv"]:
                # per-shard-row tile extrema on the mesh-common grid:
                # the fused bool engine's mask-density prune input for
                # range filter clauses (rows of absent shards have no
                # existing values -> empty intervals -> always pruned)
                mm = [build_tile_minmax(vals[i], exists[i], cap,
                                        tile=score_tile_size(cap))
                      for i in range(S)]
                if all(m is not None for m in mm):
                    entry["tile_lo"] = np.stack([m[0] for m in mm])
                    entry["tile_hi"] = np.stack([m[1] for m in mm])
            M = spec.num[f]["mv"]
            if M:
                mvv = np.zeros((S, cap, M), dtype=dtype)
                mve = np.zeros((S, cap, M), dtype=bool)
                for i, s in enumerate(shards):
                    nc = s.numerics.get(f)
                    if nc is None:
                        continue
                    if nc.mv_values is not None:
                        w = nc.mv_values.shape[1]
                        mvv[i, : s.capacity, :w] = \
                            nc.mv_values[: s.capacity].astype(dtype)
                        mve[i, : s.capacity, :w] = \
                            nc.mv_exists[: s.capacity]
                    else:
                        mvv[i, : s.capacity, 0] = nc.values.astype(dtype)
                        mve[i, : s.capacity, 0] = nc.exists
                entry["mv_values"] = mvv
                entry["mv_exists"] = mve
            arrays["num"][f] = entry
        live = np.zeros((S, cap), dtype=bool)
        for i, s in enumerate(shards):
            live[i, : s.num_docs] = True

        # placement hooks: single-host = plain device_put / numpy
        # passthrough; parallel/multihost.py swaps in callback placers
        # that serve only this host's shard rows. place_step places the
        # stepped-deadline scalar vector — HOST-LOCAL by design in a
        # multi-host mesh (each process polls its own offset-corrected
        # deadline; parallel/clocksync.py), identity elsewhere.
        self.place_params = lambda tree: tree
        self.place_aggs = lambda tree: tree
        self.place_step = lambda arr: arr
        if placer is None:
            def placer(a: np.ndarray):
                pspec = P("shard", *([None] * (a.ndim - 1)))
                return jax.device_put(jnp.asarray(a),
                                      NamedSharding(mesh, pspec))

        num_dtypes = {f: np.dtype(spec.num[f]["dtype"])
                      for f in num_fields}
        self.dev = jax.tree_util.tree_map(placer, arrays)
        self._shard_put = placer
        # sort permutations of the lazy agg layouts (kept host-side for
        # projection top-ups; one [S, cap] array per LAYOUT, not per
        # column — columns rebuild on demand from self.shards)
        self._layout_perms: dict[tuple[str, str], np.ndarray] = {}
        self.host_live = live          # host copy for incremental deletes
        self.live = placer(live)

        # per-shard union binding views (one plan shape for all shards)
        from ..index.segment import (PostingsField, KeywordColumn,
                                     NumericColumn, VectorColumn)
        import copy as _copy

        self.bind_views: list[_UnionShardView] = []
        for s in shards:
            text = {}
            for f in text_fields:
                pf = s.text.get(f)
                if pf is None:
                    pf = PostingsField(
                        name=f, terms=[], term_index={},
                        df=np.zeros(0, np.int32), indptr=np.zeros(1, np.int64),
                        doc_ids=np.zeros(0, np.int32),
                        tfs=np.zeros(0, np.float32),
                        doc_len=np.zeros(s.capacity, np.float32),
                        doc_count=0, avg_len=1.0)
                    pf.block_start = np.zeros(1, np.int32)
                    pf.fwd_tids = (None if f in self.fwd_disabled
                                   else np.zeros((0, 0), np.int32))
                elif f in self.fwd_disabled and pf.fwd_tids is not None:
                    pf = _copy.copy(pf)
                    pf.fwd_tids = None
                    pf.fwd_imps = None
                text[f] = pf
            kws = {}
            for f in kw_fields:
                # the packed kw columns hold MESH-GLOBAL ordinals, so
                # term/range/set binds must resolve against the global
                # dictionary, not the shard's local one (local-ord binds
                # against global columns silently mis-match whenever
                # shard dictionaries differ). Global df + total docs
                # also give every shard the same idf — the DFS-mode
                # scoring the distributed path wants.
                terms = self.kw_terms[f]
                kc = KeywordColumn(
                    name=f, terms=terms,
                    term_index={t: i for i, t in enumerate(terms)},
                    ords=np.full(0, -1, np.int32),
                    df=spec.kw_df[f])
                kws[f] = kc
            nums = {}
            for f in num_fields:
                # dtype-signaling stub: range/term binds must pick the
                # PACK dtype on every shard, not the local column's
                nums[f] = NumericColumn(
                    name=f, kind=spec.num[f]["kind"],
                    values=np.zeros(0, num_dtypes[f]),
                    exists=np.zeros(0, bool), raw=np.zeros(0, np.int64),
                    bias=spec.num[f]["bias"])
            vecs = {}
            for f in vec_fields:
                # dims-signaling stub: a knn clause binds to ONE desc
                # (field, similarity, pack dims) on every shard
                D = spec.vec[f]["dims"]
                vecs[f] = VectorColumn(
                    name=f, values=np.zeros((0, D), np.float32),
                    exists=np.zeros(0, bool),
                    norms=np.zeros(0, np.float32))
            self.bind_views.append(_UnionShardView(
                s, text, kws, nums, num_docs=max(spec.total_docs, 1),
                vectors=vecs))

    def _stacked_kw(self, f: str) -> np.ndarray | None:
        """[S, cap] mesh-global ordinal column rebuilt from the
        segments (same remap as the pack loop); None for mv/absent."""
        if f not in self.kw_terms or self.spec.kw_mv.get(f, 0):
            return None
        lookup = {t: i for i, t in enumerate(self.kw_terms[f])}
        ords = np.full((len(self.shards), self.cap), -1, np.int32)
        for i, s in enumerate(self.shards):
            kc = s.keywords.get(f)
            if kc is None:
                continue
            remap = np.asarray([lookup[t] for t in kc.terms], np.int32)
            local = kc.ords[: s.capacity]
            if remap.size:
                ords[i, : s.capacity] = np.where(
                    local >= 0, remap[np.clip(local, 0, None)], -1)
        return ords

    def _stacked_num(self, f: str) -> tuple[np.ndarray, np.ndarray] | None:
        """([S, cap] values, exists) in the pack dtype; None for
        mv/absent columns."""
        e = self.spec.num.get(f)
        if e is None or e["mv"]:
            return None
        dtype = e["dtype"]
        vals = np.zeros((len(self.shards), self.cap), dtype=dtype)
        exists = np.zeros((len(self.shards), self.cap), dtype=bool)
        for i, s in enumerate(self.shards):
            nc = s.numerics.get(f)
            if nc is None:
                continue
            vals[i, : s.capacity] = nc.values.astype(dtype)
            exists[i, : s.capacity] = nc.exists
        return vals, exists

    def _top_up(self, store: dict, perms: np.ndarray,
                filter_kw: set[str], filter_num: set[str]) -> None:
        """Add MISSING filter-column projections to an existing layout
        (later queries may reference different fields than the first)."""
        for g in filter_num - set(store["vw_num"]):
            col = self._stacked_num(g)
            if col is None:
                continue
            vals, exists = col
            store["vw_num"][g] = {
                "values": self._shard_put(
                    np.take_along_axis(vals, perms, 1)),
                "exists": self._shard_put(
                    np.take_along_axis(exists, perms, 1))}
        for g in filter_kw - set(store["vw_kw"]):
            ords_g = self._stacked_kw(g)
            if ords_g is None:
                continue
            store["vw_kw"][g] = self._shard_put(
                np.take_along_axis(ords_g, perms, 1))

    def ensure_sorted_layouts(self, kw_layouts: set[str],
                              num_layouts: set[str],
                              filter_kw: set[str],
                              filter_num: set[str]) -> None:
        """Stacked per-shard-row sorted layouts + view projections — the
        mesh analog of the single-chip ensure_kw_sorted /
        ensure_num_sorted / ensure_agg_views. After this, the shard_map
        program's per-shard seg slice carries the SAME structure the
        single-chip view agg path keys on, so eval_aggs routes through
        the gather-free sorted-view kernels on the mesh too. Strictly
        additive and presence-gated: packs that never call this execute
        exactly as before; the jit cache retraces on the seg pytree
        structure change, so no manual invalidation is needed."""
        S = len(self.shards)
        for f in kw_layouts:
            store = self.dev.get("kw_sorted", {}).get(f)
            if store is None:
                ords = self._stacked_kw(f)
                if ords is None:
                    continue
                card = len(self.kw_terms.get(f, []))
                perms = np.argsort(ords, axis=1, kind="stable").astype(
                    np.int32)
                starts = np.empty((S, card + 1), dtype=np.int32)
                for i in range(S):
                    starts[i] = np.searchsorted(ords[i][perms[i]],
                                                np.arange(card + 1))
                store = {"perm": self._shard_put(perms),
                         "starts": self._shard_put(starts),
                         "vw_num": {}, "vw_kw": {}, "vw_kw_mv": {}}
                self.dev.setdefault("kw_sorted", {})[f] = store
                self._layout_perms[("kw", f)] = perms
            self._top_up(store, self._layout_perms[("kw", f)],
                         filter_kw, filter_num)
        for f in num_layouts:
            store = self.dev.get("num_sorted", {}).get(f)
            if store is None:
                col = self._stacked_num(f)
                if col is None:
                    continue
                vals, exists = col
                vals = vals.copy()
                sentinel = (np.iinfo(np.int32).max
                            if vals.dtype == np.int32
                            else np.float32(np.inf))
                vals[~exists] = sentinel
                perms = np.argsort(vals, axis=1, kind="stable").astype(
                    np.int32)
                store = {
                    "perm": self._shard_put(perms),
                    "vals": self._shard_put(
                        np.take_along_axis(vals, perms, 1)),
                    "sexists": self._shard_put(
                        np.take_along_axis(exists, perms, 1)),
                    "vw_num": {}, "vw_kw": {}, "vw_kw_mv": {}}
                self.dev.setdefault("num_sorted", {})[f] = store
                self._layout_perms[("num", f)] = perms
            self._top_up(store, self._layout_perms[("num", f)],
                         filter_kw, filter_num)

    def deactivate_rows(self, rows_per_shard: dict[int, list[int]]) -> None:
        """Clear live bits for deleted/updated docs WITHOUT repacking —
        an O(corpus bitmap) upload, not an O(corpus content) rebuild
        (the mesh analog of Lucene liveDocs). Shard ids are GLOBAL;
        each host may only deactivate rows it owns."""
        changed = False
        for sid, rows in rows_per_shard.items():
            local = sid - self.shard_offset
            if not 0 <= local < len(self.shards):
                raise ValueError(
                    f"shard {sid} is outside this host's span "
                    f"[{self.shard_offset}:"
                    f"{self.shard_offset + len(self.shards)})")
            for r in rows:
                if self.host_live[local, r]:
                    self.host_live[local, r] = False
                    changed = True
        if changed:
            self.live = self._shard_put(self.host_live)

    @classmethod
    def from_node_index(cls, node, index_name: str, mesh: Mesh) -> "PackedShards":
        """Pack a Node's index (force-merging each shard to one segment)."""
        svc = node.indices[index_name]
        shards = []
        for sid in range(svc.num_shards):
            eng = svc.shard(sid)
            eng.refresh()
            if len(eng.segments) == 0:
                shards.append(SegmentBuilder().build(f"empty_{sid}"))
            else:
                # always a fresh copy: PackedShards owns its segments (it
                # may normalize forward-index availability across shards);
                # re-bake impacts with the mapped per-field similarity so
                # mesh scores match the host path (index/similarity.py)
                shards.append(merge_segments(
                    eng.segments, f"packed_{sid}", eng.live,
                    similarity=svc.mappers.similarity_for))
        return cls(index_name, shards, svc.mappers, mesh)


def _reduce_shard_axis(agg_out: dict) -> dict:
    """psum counts/sums, pmin mins, pmax maxes over the shard axis."""
    def walk(obj):
        if isinstance(obj, dict):
            out = {}
            for key, v in obj.items():
                if isinstance(v, dict):
                    out[key] = walk(v)
                elif key == "min":
                    out[key] = jax.lax.pmin(v, "shard")
                elif key == "max":
                    out[key] = jax.lax.pmax(v, "shard")
                else:
                    out[key] = jax.lax.psum(v, "shard")
            return out
        return jax.lax.psum(obj, "shard")

    return walk(agg_out)


class _PendingMesh:
    """In-flight half of a split mesh msearch: the shard_map programs of
    every signature group are enqueued; finish() collects in submission
    order. Interface-compatible with shard_searcher._PendingMsearch so
    the dispatch scheduler can pipeline mesh searchers like readers
    (including the cooperative `deadline`: collection past it raises
    SearchTimeoutError instead of syncing the remaining groups)."""

    __slots__ = ("searcher", "bodies", "parts", "group_sizes",
                 "dispatch_count", "deadline")

    def __init__(self, searcher: "DistributedSearcher", bodies: list[dict],
                 parts: list[tuple], group_sizes: list[int],
                 deadline: float | None = None):
        self.searcher = searcher
        self.bodies = bodies
        self.parts = parts
        self.group_sizes = group_sizes
        self.dispatch_count = len(parts)
        self.deadline = deadline

    def finish(self) -> list[dict]:
        import time
        out: list[dict | None] = [None] * len(self.bodies)
        for idxs, st in self.parts:
            if self.deadline is not None \
                    and time.monotonic() > self.deadline:
                raise SearchTimeoutError(
                    self.searcher.packed.index_name)
            raws = self.searcher._collect_with_failover(
                [self.bodies[i] for i in idxs], st,
                deadline=self.deadline)
            for i, raw in zip(idxs, raws):
                out[i] = DistributedSearcher._build_response(
                    self.bodies[i], [raw])
        return out  # type: ignore[return-value]


class DistributedSearcher:
    """Executes searches as one shard_map program over the mesh.

    `replica_ids` maps mesh-local replica rows to PHYSICAL full-mesh
    row ids: a degraded repack (parallel/repack.py) serves from a
    reduced mesh whose row 0 may physically be the full mesh's row 1,
    and fault-injection selectors / per-row failover counters must keep
    addressing the physical row. `health` is the optional consecutive-
    failure tracker the eviction machinery wires in at the dispatch and
    collect boundaries (timeouts and parse errors never reach it,
    matching the failover retry rules)."""

    def __init__(self, packed: PackedShards, health=None,
                 replica_ids: tuple[int, ...] | None = None,
                 gather_out: bool = False):
        self.packed = packed
        self.mesh = packed.mesh
        self.n_replicas = self.mesh.shape["replica"]
        self.health = health
        # gather_out: all_gather results over the replica axis so EVERY
        # device (hence every process) holds the full batch's output —
        # required when replica rows live on different hosts (the
        # multihost replica layout: device_get of another host's output
        # shard is not addressable); wasted bytes on a single-host mesh,
        # so it stays off there
        self._gather_out = bool(gather_out)
        self.replica_ids = (tuple(replica_ids) if replica_ids is not None
                            else tuple(range(self.n_replicas)))
        if len(self.replica_ids) != self.n_replicas:
            raise ValueError(
                f"{len(self.replica_ids)} replica_ids for a "
                f"{self.n_replicas}-replica mesh")
        self._jit_cache: dict = {}

    def _phys(self, replica: int) -> int:
        """Mesh-local replica row -> physical full-mesh row id."""
        return self.replica_ids[replica]

    def adopt_pack(self, packed: PackedShards) -> bool:
        """Swap in a REBUILT pack (the streaming tail's refresh epoch
        bump) while keeping every pinned shard_map program: legal
        exactly when the new pack's device-tree avals match the old —
        the compiled programs take the pack as a runtime argument, so
        identical shapes/dtypes mean zero recompiles, they just read
        the new epoch's columns. PackSpec pow2-buckets every content-
        proportional dimension (cap, nb, fwd_l, nt), so a growing tail
        only mismatches when a bucket overflows — then the caller
        rebuilds the searcher, paying the compile log-many times
        instead of once per refresh. Returns False on any mismatch."""
        if packed.mesh is not self.mesh:
            return False
        old = (self.packed.dev, self.packed.live)
        new = (packed.dev, packed.live)
        if jax.tree_util.tree_structure(old) \
                != jax.tree_util.tree_structure(new):
            return False
        for a, b in zip(jax.tree_util.tree_leaves(old),
                        jax.tree_util.tree_leaves(new)):
            if tuple(a.shape) != tuple(b.shape) or a.dtype != b.dtype:
                return False
        self.packed = packed
        from ..search import resident
        if resident.enabled() and self._jit_cache:
            # every pinned program that survived the epoch bump is one
            # avoided mesh recompile — reported through the same
            # counters the repack's drops go through
            resident.stats.refresh_reuses.inc(len(self._jit_cache))
        return True

    # -- public ------------------------------------------------------------
    def search(self, body: dict) -> dict:
        return self.msearch([body])[0]

    def msearch(self, bodies: list[dict],
                with_partials: bool = False,
                deadline: float | None = None) -> list[dict]:
        """Heterogeneous batch: bodies group by (plan signature, aggs),
        one device program per group — the mesh analog of the host
        path's signature grouping in shard_searcher.msearch. Each body
        keeps its OWN aggregations. (with_partials is accepted for
        scheduler interface parity — the sync and isolated-retry paths
        of search/dispatch.py call reader.msearch(bodies, wp) — and is
        ignored: mesh responses are always complete.)"""
        pend = self.msearch_submit(bodies, deadline=deadline)
        out = pend.finish()
        from ..search.dispatch import note_submit_stats
        note_submit_stats(pend.group_sizes, pend.dispatch_count)
        return out

    def msearch_submit(self, bodies: list[dict],
                       with_partials: bool = False,
                       deadline: float | None = None) -> "_PendingMesh":
        """The batched dispatch entry the scheduler (search/dispatch.py)
        expects: every signature group's shard_map program is enqueued
        WITHOUT a device sync; finish() collects in submission order.
        Group dispatches are pipelined exactly like the single-chip
        executor's — the mesh accepts the same batched entry.
        (with_partials is accepted for interface parity; mesh responses
        are always complete.)"""
        bodies = self._rewrite_knn(bodies)
        parts = []
        groups = self._signature_groups(bodies)
        for idxs in groups.values():
            parts.append((idxs,
                          self._dispatch_uniform([bodies[i]
                                                  for i in idxs],
                                                 deadline=deadline)))
        return _PendingMesh(self, bodies, parts,
                            group_sizes=[len(i) for i in groups.values()],
                            deadline=deadline)

    def _rewrite_knn(self, bodies: list[dict]) -> list[dict]:
        """Top-level `knn` sections rewrite onto the knn SCORING CLAUSE
        (search/shard_searcher.rewrite_knn_body — one rewrite, both
        substrates): the mesh serves vector search through the same
        shard_map program as everything else, so sharding, replica
        failover, eviction-repack, and host elasticity cover it with
        no dedicated path. Pure-knn bodies clamp size to k (the knn
        candidate-window contract) but report `hits.total` as the
        MATCH count (every live doc carrying a vector) — the mesh has
        no candidates path, so totals/aggs are query-shaped here where
        the single-chip candidates path reports the k-window
        (documented divergence; the hit window itself is identical)."""
        if not any((b or {}).get("knn") for b in bodies):
            return bodies
        from ..search.shard_searcher import rewrite_knn_body
        out = []
        for b in bodies:
            if (b or {}).get("knn"):
                _fused_stats.record_knn("mesh:query_rewrite")
                nb = rewrite_knn_body(b)
                if not b.get("query"):
                    k = int(b["knn"].get("k",
                                         b["knn"].get("num_candidates",
                                                      10)))
                    nb["size"] = min(int(b.get("size", 10)), k)
                b = nb
            out.append(b)
        return out

    def raw_msearch(self, bodies: list[dict],
                    deadline: float | None = None,
                    allow_stepped: bool | None = None) -> list[dict]:
        """Per-body raw results (candidates + agg partials) for callers
        that merge across generations (MeshIndex) or fetch across hosts
        (MultiHostIndex). `deadline` is absolute LOCAL monotonic
        seconds (a multihost caller passes its offset-corrected copy of
        the driver's deadline); `allow_stepped` overrides the stepped-
        program auto-gate — the multihost driver decides ONCE and
        broadcasts the decision so every process compiles the same
        program form (a per-host decision could diverge and deadlock
        the mesh in a collective)."""
        bodies = self._rewrite_knn(bodies)
        out: list[dict | None] = [None] * len(bodies)
        for idxs in self._signature_groups(bodies).values():
            raws = self._raw_uniform([bodies[i] for i in idxs],
                                     deadline=deadline,
                                     allow_stepped=allow_stepped)
            for i, raw in zip(idxs, raws):
                out[i] = raw
        return out  # type: ignore[return-value]

    def _signature_groups(self, bodies: list[dict]) -> dict:
        pk = self.packed
        parser = QueryParser(pk.mappers)
        binder = QueryBinder(pk.bind_views[0], pk.mappers)  # type: ignore
        groups: dict[tuple, list[int]] = {}
        for i, b in enumerate(bodies):
            sig = binder.bind(parser.parse(b.get("query"))).signature()
            aggs_key = json.dumps(b.get("aggs") or b.get("aggregations")
                                  or {}, sort_keys=True, default=str)
            k = int(b.get("size", 10)) + int(b.get("from", 0))
            groups.setdefault((sig, aggs_key, k), []).append(i)
        return groups

    def _raw_uniform(self, bodies: list[dict],
                     deadline: float | None = None,
                     allow_stepped: bool | None = None) -> list[dict]:
        """One compiled program for structurally identical bodies ->
        per-body {"score", "shard", "doc", "total", "partials",
        "agg_specs", "packed"}."""
        return self._collect_with_failover(
            bodies, self._dispatch_uniform(bodies, deadline=deadline,
                                           allow_stepped=allow_stepped),
            deadline=deadline, allow_stepped=allow_stepped)

    def _collect_with_failover(self, bodies: list[dict], st: dict,
                               deadline: float | None = None,
                               allow_stepped: bool | None = None
                               ) -> list[dict]:
        """Collect with the OTHER half of replica failover: jax
        dispatch is asynchronous, so a real device failure (preemption,
        tunnel drop, OOM) usually surfaces at the device_get inside
        _collect_uniform, not at enqueue — on such an error the whole
        dispatch+collect is re-entered once per remaining replica row.
        Deadline and request-shaped errors never retry, and a deadline
        that passes MID-failover stops the retry loop with the same
        SearchTimeoutError the pending path raises (re-dispatching
        cannot un-pass the cutoff; it only burns device time) — with no
        holds retained, so the failover-exhaustion exit leaks nothing."""
        import time
        rep0 = int(st.get("replica", 0))
        try:
            out = self._collect_uniform(st)
        except (SearchTimeoutError, *_PARSE_ERRORS):
            raise
        except Exception as e:  # noqa: BLE001 — device/injected
            from ..search.dispatch import failover_stats
            if self.health is not None:
                self.health.record_failure(self._phys(rep0), e)
            last: Exception = e
            for rep in range(rep0 + 1, self.n_replicas):
                if deadline is not None and time.monotonic() > deadline:
                    raise SearchTimeoutError(self.packed.index_name)
                failover_stats.record_retry(self._phys(rep))
                try:
                    out = self._collect_uniform(
                        self._dispatch_uniform_attempt(
                            bodies, rep, deadline=deadline,
                            allow_stepped=allow_stepped))
                except (SearchTimeoutError, *_PARSE_ERRORS):
                    raise
                except Exception as e2:  # noqa: BLE001
                    if self.health is not None:
                        self.health.record_failure(self._phys(rep), e2)
                    last = e2
                    continue
                failover_stats.record_succeeded(self._phys(rep))
                if self.health is not None:
                    self.health.record_success(self._phys(rep))
                return out
            if self.n_replicas > 1:
                failover_stats.record_failed(self._phys(rep0))
            raise last
        if self.health is not None:
            self.health.record_success(self._phys(rep0))
        return out

    def _check_shard_rows(self, replica: int) -> None:
        """Mesh dispatch boundary of the fault-injection registry
        (utils/faults.py): one probe per LOCAL shard row, carrying the
        PHYSICAL replica row this attempt runs against so rules can pin
        a fault to one copy (`shard_error:shard=2:replica=0:site=mesh`)
        and a rule pinned to an evicted row never re-fires against the
        survivor that inherited its mesh-local index after a repack."""
        from ..utils import faults
        if not faults.enabled():
            return
        pk = self.packed
        for local in range(len(pk.shards)):
            faults.on_dispatch("mesh", index=pk.index_name,
                               shard=pk.shard_offset + local,
                               replica=self._phys(replica))

    def _dispatch_uniform(self, bodies: list[dict],
                          deadline: float | None = None,
                          allow_stepped: bool | None = None) -> dict:
        """Dispatch half of _raw_uniform with replica failover
        (TransportSearchTypeAction.onFirstPhaseResult's retry of the
        next shard routing, mapped onto the mesh): when an attempt
        fails (real device/dispatch error OR injected fault) and the
        mesh has more replica rows (n_replicas > 1), the dispatch is
        re-entered once per extra replica row before giving up.
        Request-shaped errors (parse) never retry: every copy would
        reject them the same way.

        Scope note: a retry RE-ENTERS the same SPMD program — the
        collective spans every replica row, so this recovers TRANSIENT
        failures (preempted queue, tunnel drop, an injected fault
        pinned to one replica row via `replica=`), which is what
        replication buys without resharding. A device that is
        permanently dead fails every re-entry; the wired-in `health`
        tracker counts those consecutive failures and, past
        `mesh.eviction.failure_threshold`, triggers the degraded repack
        onto the surviving rows (parallel/repack.py) that removes the
        per-search tax. Counters:
        nodes_stats()["dispatch"]["failover"]."""
        from ..search.dispatch import failover_stats
        last: Exception | None = None
        for rep in range(self.n_replicas):
            if rep > 0:
                failover_stats.record_retry(self._phys(rep))
            try:
                out = self._dispatch_uniform_attempt(
                    bodies, rep, deadline=deadline,
                    allow_stepped=allow_stepped)
            except _PARSE_ERRORS:
                raise
            except Exception as e:  # noqa: BLE001 — device/injected
                if self.health is not None:
                    self.health.record_failure(self._phys(rep), e)
                last = e
                continue
            if rep > 0:
                failover_stats.record_succeeded(self._phys(rep))
            return out
        if self.n_replicas > 1:
            failover_stats.record_failed(self._phys(0))
        assert last is not None
        raise last

    def _dispatch_uniform_attempt(self, bodies: list[dict],
                                  replica: int,
                                  deadline: float | None = None,
                                  allow_stepped: bool | None = None
                                  ) -> dict:
        """One dispatch attempt against one replica row's copies: bind,
        admit, and enqueue the shard_map program WITHOUT syncing, so
        several groups' (or several searchers') programs can be in
        flight at once. A `deadline` (absolute monotonic seconds) on a
        fused-admitted plan arms the STEPPED program form — the chunked
        tile walk with the collective-safe per-chunk deadline check —
        so a laggard mesh search exits early from the device instead of
        completing its whole walk (the cooperative _PendingMesh check
        only fires once results are already computed)."""
        self._check_shard_rows(replica)
        pk = self.packed
        n = len(bodies)
        parser = QueryParser(pk.mappers)
        queries = [parser.parse(b.get("query")) for b in bodies]
        sizes = [int(b.get("size", 10)) + int(b.get("from", 0))
                 for b in bodies]
        k = min(next_pow2(max(max(sizes), 1), floor=1), pk.cap)
        agg_specs = parse_aggs(bodies[0].get("aggs")
                               or bodies[0].get("aggregations"))
        for spec in agg_specs:
            fm = pk.mappers.field(spec.field)
            if spec.kind in ("terms", "cardinality", "value_count") and \
                    fm is not None and fm.type == "text" and \
                    pk.mappers.field(f"{spec.field}.keyword") is not None:
                spec.field = f"{spec.field}.keyword"

        # pad batch to a replica-axis multiple
        R = self.n_replicas
        B = ((max(n, 1) + R - 1) // R) * R
        queries = queries + [queries[0]] * (B - n)

        # bind per (shard, query) against the UNION views; ONE finalize
        # over the flattened batch guarantees identical desc across shards
        flat_bounds = []
        for view in pk.bind_views:
            binder = QueryBinder(view, pk.mappers)  # type: ignore[arg-type]
            flat_bounds.extend(binder.bind(q) for q in queries)
        sig0 = flat_bounds[0].signature()
        for bnd in flat_bounds[1:]:
            if bnd.signature() != sig0:
                raise SearchParseError(
                    "distributed msearch requires structurally identical "
                    "queries (split heterogeneous batches)")
        desc, flat_params = finalize(flat_bounds)  # leaves [S_local*B, ...]
        params = jax.tree_util.tree_map(
            lambda a: a.reshape(len(pk.bind_views), B, *a.shape[1:]),
            flat_params)
        params = pk.place_params(params)

        agg_desc, agg_params = self._build_aggs(agg_specs)
        agg_params = pk.place_aggs(agg_params)

        # sorted-view agg layouts (presence-gated, like single-chip):
        # when the query is view-compatible, pack stacked sorted layouts
        # + filter-column projections so the in-program agg mask never
        # rides a per-query permutation gather
        filter_kw: set = set()
        filter_num: set = set()
        if agg_specs and pk.shard_offset == 0 \
                and len(pk.shards) == pk.n_shards \
                and _bound_view_fields(flat_bounds[0], filter_kw,
                                       filter_num):
            kw_layouts = {s.field for s in agg_specs if s.kind == "terms"}
            num_layouts = {s.field for s in agg_specs
                           if s.kind in ("date_histogram", "histogram",
                                         "percentiles",
                                         "percentile_ranks")}
            sub_nums = {m.field for s in agg_specs
                        for m in getattr(s, "sub_metrics", ())}
            pk.ensure_sorted_layouts(kw_layouts, num_layouts, filter_kw,
                                     filter_num | sub_nums)

        # fused block-max score+top-k routing: the SAME plan classifier
        # as the single-chip executor (the mesh program is
        # score-sort-only, hence the literal sort_spec; the mesh fused
        # branch computes no aggs, so agg plans fall back), over a pack
        # that carries per-shard-row tile summaries, with positive bool
        # boosts. Every admission input is identical on every host, so
        # the SPMD entry stays collective.
        fused = None
        bundle, reject = _fused_plan_bundle(desc, min(k, pk.cap),
                                            agg_specs, ("_score",),
                                            allow_aggs=False)
        if bundle is not None:
            from ..ops.scoring import positional_prefix, clause_fields
            for _r, kd, f, _w in bundle:
                if kd in _FUSED_DENSE_KINDS:
                    if "tile_max" not in pk.dev["text"].get(f, {}):
                        bundle, reject = None, "missing_tile_max"
                        break
                elif isinstance(kd, str) and positional_prefix(kd):
                    # every clause field needs the packed positional
                    # slab AND tile summaries (spec packs them only
                    # when every shard on every host carries positions)
                    if any("fwd_pos" not in pk.dev["text"].get(cf, {})
                           or "tile_max" not in pk.dev["text"].get(cf, {})
                           for cf in clause_fields(f)):
                        bundle, reject = None, "missing_positions_pack"
                        break
                elif kd in _FUSED_VEC_KINDS:
                    if f not in pk.dev.get("vec", {}):
                        bundle, reject = None, "missing_vector_column"
                        break
                elif "tile_lo" not in pk.dev["num"].get(f, {}):
                    bundle, reject = None, "missing_tile_minmax"
                    break
        if bundle is not None and not _fused_params_ok(desc, flat_params,
                                                       bundle):
            bundle, reject = None, "nonpositive_boost"
        if bundle is not None:
            ck = min(min(k, pk.cap), score_tile_size(pk.cap))
            pallas_reason = _bundle_pallas_reason(
                bundle, (), ck, _bundle_pos_width(bundle, pk.dev["text"]))
            if pallas_reason is not None:
                _fused_stats.record_pallas_reject(pallas_reason)
            # an SPMD program cannot wall-clock itself per host without
            # desyncing the collective (run_backend=None), but it CAN
            # reuse a choice the single-chip executor timed + persisted
            # for an identical pack: the per-shard fingerprints key the
            # same canonical store entries (autotune_persist_key)
            backend = resolve_fused_backend(
                ("mesh", pk.index_name, pk.cap, desc, k), ck,
                pallas_candidate=pallas_reason is None,
                # keyed by each shard's OWN capacity: that is the cap a
                # single-chip execution of the content-identical segment
                # persisted under (capacity is content-derived, so it
                # matches exactly when the fingerprint does — pk.cap is
                # the mesh-wide pad and would silently never match).
                # seg_cache_key (not fingerprint): a streaming TAIL
                # shard keys on its (base generation, pow2 extent), so
                # a refreshed tail keeps hitting the same entry
                persist_keys=tuple(autotune_persist_key(
                    seg_cache_key(s), s.capacity, desc, k, False)
                    for s in pk.shards))
            fused = (bundle, backend)
            _fused_stats.record_admit(
                positional=_bundle_positional(bundle))
        else:
            _fused_stats.record_reject(reject)
        stepped = (fused is not None and deadline is not None
                   and (allow_stepped if allow_stepped is not None
                        else _mesh_stepped_enabled()))
        run = self._compiled(desc, agg_desc, k, B // R, fused,
                             stepped=stepped)
        if stepped:
            hi, lo = _split_deadline(deadline)
            step_arr = pk.place_step(
                jnp.asarray([hi, lo, 0.0, 0.0], jnp.float32))
            out = run(pk.dev, pk.live, params, agg_params, step_arr)
        else:
            out = run(pk.dev, pk.live, params, agg_params)
        return {"out": out, "stepped": stepped,
                "fused": fused, "agg_specs": agg_specs,
                # captured NOW: a later _build_aggs (another group's
                # dispatch before this one collects) must not clobber it
                "agg_ctx": self._agg_ctx, "n": n, "B": B,
                # which replica row's copies this attempt ran against —
                # the collect probe and collect-time failover key on it
                "replica": replica}

    def _collect_uniform(self, st: dict) -> list[dict]:
        """Collect half of _raw_uniform: sync + build per-body raws."""
        pk = self.packed
        # collect-phase fault boundary (mirrors the reader's): straggler
        # rules (shard_delay defaults to phase=collect) burn wall-clock
        # here, where the caller waits on the collective's results —
        # _PendingMesh.finish's deadline check then times out the
        # still-uncollected groups
        from ..utils import faults
        if faults.enabled():
            for local in range(len(pk.shards)):
                faults.on_dispatch("mesh", index=pk.index_name,
                                   shard=pk.shard_offset + local,
                                   replica=self._phys(
                                       int(st.get("replica", 0))),
                                   phase="collect")
        n, B = st["n"], st["B"]
        agg_specs = st["agg_specs"]
        if st.get("stepped"):
            # the psum'd device-side verdict: ANY shard's chunk walk
            # crossing the deadline times the whole search out — its
            # skipped chunks make the gathered results unusable, which
            # is exactly the discard-on-timeout contract the
            # cooperative path already has
            (m_score, m_shard, m_doc, total, prune), agg_out, timed = \
                jax.device_get(st["out"])
            if int(timed) > 0:
                from ..search import resident as _resident
                _resident.stats.preempted_by_deadline.inc()
                raise SearchTimeoutError(pk.index_name)
        else:
            (m_score, m_shard, m_doc, total, prune), agg_out = \
                jax.device_get(st["out"])
        if st["fused"] is not None:
            # prune rows are the mesh-wide (shard AND replica psum'd)
            # dispatch totals, replicated per query row — one record
            # per dispatch
            _fused_stats.record_prune(
                *(float(x) for x in prune[0]),
                positional=_bundle_positional(st["fused"][0]))

        per_query_partials = [None] * B
        if agg_specs:
            per_query_partials = shard_partials(
                agg_specs, st["agg_ctx"],
                [jax.tree_util.tree_map(np.asarray, agg_out)], batch=B)
        return [{"score": m_score[i], "shard": m_shard[i],
                 "doc": m_doc[i], "total": int(total[i]),
                 "partials": per_query_partials[i],
                 "agg_specs": agg_specs, "packed": pk}
                for i in range(n)]

    @staticmethod
    def _build_response(body: dict, raws: list[dict]) -> dict:
        """Merge one body's raw results from 1+ generations (base/tail
        packs) into a response — the cross-generation sortDocs + agg
        reduce."""
        frm = int(body.get("from", 0))
        size = int(body.get("size", 10))
        cands = []
        total = 0
        for gen, raw in enumerate(raws):
            total += raw["total"]
            nvalid = int(min(raw["total"], raw["score"].shape[0]))
            for j in range(nvalid):
                cands.append((-float(raw["score"][j]), gen,
                              int(raw["shard"][j]), int(raw["doc"][j])))
        cands.sort()
        hits = []
        for negs, gen, s, d in cands[frm: frm + size]:
            pk = raws[gen]["packed"]
            local = s - pk.shard_offset
            if not 0 <= local < len(pk.shards):
                raise RuntimeError(
                    f"hit on shard {s} lives on another host — fetch "
                    "multi-host results through MultiHostIndex, not "
                    "DistributedSearcher directly")
            seg = pk.shards[local]
            hits.append({
                "_index": raws[gen]["packed"].index_name,
                "_type": "_doc",
                "_id": seg.ids[d],
                "_score": -negs,
                "_source": json.loads(seg.sources[d]),
            })
        pk0 = raws[0]["packed"]
        resp = {
            "took": 0, "timed_out": False,
            "_shards": shards_header(pk0.n_shards, pk0.n_shards),
            "hits": {"total": total,
                     "max_score": (-cands[0][0]) if cands else None,
                     "hits": hits},
        }
        agg_specs = raws[0]["agg_specs"]
        if agg_specs:
            merged = merge_shard_partials(
                agg_specs, [r["partials"] for r in raws
                            if r["partials"] is not None])
            resp["aggregations"] = finalize_partials(agg_specs, merged)
        return resp

    # -- aggs --------------------------------------------------------------
    def _build_aggs(self, specs: list[AggSpec]):
        pk = self.packed
        self._agg_ctx = None
        if not specs:
            return (), ()
        global_ords = {}
        for s in specs:
            if s.kind in ("terms", "cardinality"):
                terms = pk.kw_terms.get(s.field, [])
                ident = np.arange(max(len(terms), 1), dtype=np.int32)
                # identity maps: packed columns already hold mesh-global ords
                global_ords[s.field] = (terms, [ident] * pk.n_shards)
        extents = {
            f: (None if e["ext"] is None
                else (e["ext"][0], e["ext"][1],
                      np.dtype(e["dtype"]) == np.int32))
            for f, e in pk.spec.num.items()}
        self._agg_ctx = ShardAggContext(pk.shards, global_ords,
                                        allow_device_topk=False,
                                        extent_override=extents)
        agg_desc, per_seg = self._agg_ctx.build(specs)
        if not per_seg:
            return agg_desc, ()
        stacked = jax.tree_util.tree_map(lambda *xs: np.stack(xs), *per_seg)
        return agg_desc, stacked

    # -- the distributed program ------------------------------------------
    def _compiled(self, desc, agg_desc, k: int, b_loc: int,
                  fused: tuple | None = None, stepped: bool = False):
        """One pinned shard_map program per (plan signature, agg sig,
        pow2 k, local batch, stepped?) — k arrives pow2-bucketed from
        _dispatch_uniform_attempt, so this cache IS the mesh's resident
        entry table, scoped to one immutable pack: a repack rebuilds
        PackedShards AND this searcher, so a stale program dies with
        the instance and can never serve the new pack (no fingerprint
        key needed — the per-shard fingerprints are constant for the
        life of the cache). With ES_TPU_RESIDENT_LOOP set, reuse is
        reported through the resident counters.

        The STEPPED variant (deadline-carrying fused searches) takes an
        extra replicated step_arr input and returns the psum'd
        device-side timed_out verdict: the fused tile walk runs in
        _RESIDENT_CHUNKS chunks with a host-clock poll between chunks —
        the same chunked form (XLA fori span or chunked pallas_call
        grid) the resident loop pins — and NO collectives inside the
        chunk loop, so a per-device verdict cannot desync the mesh; the
        final psum over BOTH axes makes the timeout decision
        collective. Deadline-less searches keep the callback-free
        single-walk program."""
        from ..search import resident as _resident
        key = (desc, agg_desc, k, b_loc, fused, stepped)
        fn = self._jit_cache.get(key)
        if fn is not None:
            if _resident.enabled():
                _resident.stats.resident_hits.inc()
            return fn
        if _resident.enabled():
            _resident.stats.cold_dispatches.inc()
        pk = self.packed
        mesh = self.mesh
        cap = pk.cap
        chunk_tiles = 1
        if stepped:
            f0 = next(f for _r, kd, f, _w in fused[0]
                      if kd in _FUSED_DENSE_KINDS)
            n_tiles = pk.dev["text"][f0]["tile_max"].shape[-1]
            chunk_tiles = max(1, -(-n_tiles // _RESIDENT_CHUNKS))

        gather_out = self._gather_out
        in_specs = (P("shard"), P("shard"), P("shard", "replica"),
                    P("shard"))
        if gather_out:
            # results all_gather'd over "replica" in-program: every
            # device (hence every HOST of a multi-process replica
            # layout) holds the full batch's output, so collect never
            # reads a non-addressable shard
            out_specs = ((P(), P(), P(), P(), P()), P())
        else:
            out_specs = ((P("replica"), P("replica"), P("replica"),
                          P("replica"), P("replica")), P("replica"))
        if stepped:
            in_specs = in_specs + (P(),)
            out_specs = out_specs + (P(),)

        @partial(shard_map, mesh=mesh, in_specs=in_specs,
                 out_specs=out_specs, check_vma=False)
        def program(seg, live, prm, agg_prm, *step_in):
            # b_loc is STATIC (B / replicas): param-less plans (e.g. a
            # term absent from every shard binds to a constant) carry
            # no leaf to infer the batch from
            seg = jax.tree_util.tree_map(lambda a: a[0], seg)
            live_l = live[0]
            prm_l = jax.tree_util.tree_map(lambda a: a[0], prm)
            agg_l = jax.tree_util.tree_map(lambda a: a[0], agg_prm)
            timed = None

            if fused is not None:
                # same fused block-max score+top-k engine as the
                # single-chip executor; each shard prunes against its
                # own per-clause tile summaries and never materializes
                # [B, cap] (admission guarantees no aggs, so the match
                # mask is never needed)
                f_bundle, f_backend = fused
                if stepped:
                    step = _resident_step(step_in[0], chunk_tiles)
                    l_score, l_idx, l_total, pruned, timed = \
                        eval_fused_topk(seg, desc, prm_l, live_l,
                                        min(k, cap), f_bundle,
                                        f_backend, step=step)
                else:
                    l_score, l_idx, l_total, pruned = eval_fused_topk(
                        seg, desc, prm_l, live_l, min(k, cap), f_bundle,
                        f_backend)
                agg_out = {}
            else:
                score, match = eval_node(desc, prm_l, seg, cap, b_loc)
                valid = match & live_l[None, :]
                score = jnp.where(valid, score, 0.0)
                l_score, l_idx, l_total = top_k_hits(score, valid,
                                                     min(k, cap))
                pruned = jnp.zeros((3,), jnp.float32)

                # sorted-view agg path (same machinery as the
                # single-chip executor): live masks permuted into each
                # layout's order in-program (once per dispatch), plan
                # gates per agg node
                live_views = {}
                for f, store in seg.get("kw_sorted", {}).items():
                    live_views[("kw", f)] = jnp.take(live_l, store["perm"])
                for f, store in seg.get("num_sorted", {}).items():
                    live_views[("num", f)] = jnp.take(live_l,
                                                      store["perm"])
                plan = _agg_view_plan(desc, agg_desc, agg_l, seg,
                                      live_views)
                views = _ViewMasks(desc, prm_l, seg, live_views, cap,
                                   b_loc)
                agg_out = eval_aggs(agg_desc, agg_l, seg, valid,
                                    views=views, plan=plan)

            # ---- cross-shard reduce over ICI (SearchPhaseController) ----
            g_score = jax.lax.all_gather(l_score, "shard")   # [S, b, k]
            g_idx = jax.lax.all_gather(l_idx, "shard")
            S = g_score.shape[0]
            kk = l_score.shape[1]
            # shard-major flatten => top_k tie-break = (shard asc, rank asc)
            flat_score = jnp.moveaxis(g_score, 0, 1).reshape(b_loc, S * kk)
            flat_idx = jnp.moveaxis(g_idx, 0, 1).reshape(b_loc, S * kk)
            shard_of = jnp.repeat(jnp.arange(S, dtype=jnp.int32), kk)[None, :]
            m_score, m_pos = jax.lax.top_k(flat_score, kk)
            m_shard = jnp.take_along_axis(
                jnp.broadcast_to(shard_of, flat_idx.shape), m_pos, axis=1)
            m_doc = jnp.take_along_axis(flat_idx, m_pos, axis=1)
            total = jax.lax.psum(l_total, "shard")

            # psum over BOTH axes: each replica prunes against its own
            # sub-batch, so shard-only totals would drop every replica
            # but the one whose rows land first in the gathered output
            prune = jnp.broadcast_to(
                jax.lax.psum(pruned, ("shard", "replica"))[None, :],
                (b_loc, 3))
            agg_out = _reduce_shard_axis(agg_out)
            if gather_out:
                # batch-axis gather over the replica rows (tiled: row
                # r's [b_loc] slice lands at rows r*b_loc..): identical
                # host-side shapes to the sharded out_specs, now
                # replicated on every device
                def _rep(x):
                    return jax.lax.all_gather(x, "replica", axis=0,
                                              tiled=True)
                m_score, m_shard, m_doc, total, prune = (
                    _rep(m_score), _rep(m_shard), _rep(m_doc),
                    _rep(total), _rep(prune))
                agg_out = jax.tree_util.tree_map(_rep, agg_out)
            out = ((m_score, m_shard, m_doc, total, prune), agg_out)
            if stepped:
                # collective verdict: any device's walk crossing the
                # deadline times out the whole search (both axes — a
                # replica row's laggard is as fatal as a shard's)
                out = out + (jax.lax.psum(timed.astype(jnp.int32),
                                          ("shard", "replica")),)
            return out

        fn = jax.jit(program)
        self._jit_cache[key] = fn
        return fn


class MeshIndex:
    """A LIVE mesh-resident index: big immutable base pack + small tail
    pack + liveDocs-style deletes, so the distributed path serves an
    index that is still being written to.

    Refresh semantics (the mesh analog of InternalEngine.refresh
    :549-555 — Lucene's big-segments-plus-small-segments shape mapped
    onto PackedShards):

    * docs deleted or updated since the base pack: their base rows are
      DEACTIVATED in place (one bitmap upload, no repack);
    * docs new or updated since the base pack: rebuilt into a TAIL
      PackedShards whose cost is proportional to the DELTA, not the
      corpus;
    * when the tail outgrows `repack_ratio` of the base, everything
      folds into a fresh base pack (the merge/force-merge analog).

    Searches run on base and tail programs and merge per body:
    candidates by (score desc, generation, shard, doc), totals summed,
    agg partials merged by bucket key (ordinal spaces differ between
    packs; partials are keyed by term strings / numeric keys exactly so
    they can meet).
    """

    REPACK_MIN = 4096

    def __init__(self, node, index_name: str, mesh: Mesh,
                 repack_ratio: float = 0.25):
        self.node = node
        self.index_name = index_name
        self.mesh = mesh
        self.repack_ratio = repack_ratio
        self.last_refresh_stats: dict = {}
        self._full_pack()

    # -- packing -----------------------------------------------------------

    def _full_pack(self) -> None:
        self.base = PackedShards.from_node_index(
            self.node, self.index_name, self.mesh)
        self.base_searcher = DistributedSearcher(self.base)
        # per-shard id -> (row, version) of the packed docs
        self.base_docs: list[dict[str, tuple[int, int]]] = []
        for seg in self.base.shards:
            self.base_docs.append({
                did: (row, int(seg.versions[row]))
                for did, row in seg.id_map.items()})
        self.tail: PackedShards | None = None
        self.tail_searcher: DistributedSearcher | None = None
        # signature of the delta the current tail pack was built from:
        # an unchanged delta skips the rebuild AND keeps the compiled
        # programs warm
        self._tail_sig: tuple | None = None
        # tail generation key: the mesh analog of the engine's
        # (base generation, delta epoch) — tail shards carry it as
        # their delta_parent so every fingerprint-keyed cache they
        # touch (autotune persist keys) survives the per-refresh
        # rebuild; a repack mints a new one
        import hashlib
        h = hashlib.blake2b(digest_size=8)
        for seg in self.base.shards:
            h.update(seg.fingerprint().encode())
        self._base_gen = f"mesh:{h.hexdigest()}"
        self._tail_epoch = 0

    def refresh(self) -> dict:
        """Fold engine changes into the mesh view. Returns stats:
        {"mode": "noop"|"tail"|"repack", "tail_docs": n,
        "deactivated": n}."""
        svc = self.node.indices[self.index_name]
        n_shards = self.base.n_shards
        deactivate: dict[int, list[int]] = {}
        deltas: list[list[tuple[str, int, bytes]]] = []
        total_delta = 0
        base_total = sum(s.num_docs for s in self.base.shards)
        for sid in range(n_shards):
            eng = svc.shard(sid)
            eng.refresh()
            current = {did: (ver, src)
                       for did, ver, src in eng.snapshot_docs()}
            packed = self.base_docs[sid]
            base_seg = self.base.shards[sid]

            def changed(did: str, ver: int, src: bytes) -> bool:
                entry = packed.get(did)
                if entry is None:
                    return True
                row, base_ver = entry
                if base_ver != ver:
                    return True
                # force/external_gte writes can REPLACE a doc keeping
                # the same version — the bytes are the tiebreaker
                return base_seg.sources[row] != src

            dead = [row for did, (row, ver) in packed.items()
                    if did not in current
                    or changed(did, *current[did])]
            if dead:
                deactivate[sid] = dead
            delta = [(did, ver, src)
                     for did, (ver, src) in current.items()
                     if changed(did, ver, src)]
            deltas.append(delta)
            total_delta += len(delta)

        threshold = max(base_total * self.repack_ratio, self.REPACK_MIN)
        if total_delta > threshold:
            self._full_pack()
            self.last_refresh_stats = {"mode": "repack",
                                       "tail_docs": total_delta,
                                       "deactivated": 0}
            return self.last_refresh_stats

        n_dead = sum(len(v) for v in deactivate.values())
        if deactivate:
            self.base.deactivate_rows(deactivate)
        if total_delta == 0:
            if self.tail is not None:
                # deletions may have emptied the tail
                self.tail = None
                self.tail_searcher = None
                self._tail_sig = None
            self.last_refresh_stats = {"mode": "noop",
                                       "tail_docs": 0,
                                       "deactivated": n_dead}
            return self.last_refresh_stats

        import zlib
        sig = tuple(tuple(sorted((did, ver, zlib.crc32(s))
                                 for did, ver, s in delta))
                    for delta in deltas)
        if sig == self._tail_sig and self.tail is not None:
            # nothing changed since the current tail pack was built —
            # keep it (and its compiled programs) instead of rebuilding
            self.last_refresh_stats = {"mode": "noop",
                                       "tail_docs": total_delta,
                                       "deactivated": n_dead}
            return self.last_refresh_stats

        svc_mappers = svc.mappers
        self._tail_epoch += 1
        tail_segs = []
        for sid, delta in enumerate(deltas):
            builder = SegmentBuilder(similarity=svc_mappers.similarity_for)
            for did, ver, src in sorted(delta):
                builder.add(svc_mappers.parse(did, src), version=ver)
            seg = builder.build(f"tail_{sid}")
            # generation-preserving refresh: the tail shard keys its
            # caches on (base generation, pow2 extent) and its term-
            # count-derived shapes bucket to pow2, so the rebuilt pack
            # usually lands on the SAME avals and the searcher below
            # ADOPTS it — pinned shard_map programs survive untouched
            seg.delta_parent = self._base_gen
            seg.delta_epoch = self._tail_epoch
            pad_delta_shapes(seg)
            tail_segs.append(seg)
        new_tail = PackedShards(self.index_name, tail_segs,
                                svc_mappers, self.mesh)
        reused = (self.tail_searcher is not None
                  and self.tail_searcher.adopt_pack(new_tail))
        self.tail = new_tail
        if not reused:
            # first tail, or a pow2 bucket overflowed: one rebuild
            self.tail_searcher = DistributedSearcher(new_tail)
        self._tail_sig = sig
        self.last_refresh_stats = {"mode": "tail",
                                   "tail_docs": total_delta,
                                   "deactivated": n_dead,
                                   "tail_programs_reused": bool(reused)}
        return self.last_refresh_stats

    # -- search ------------------------------------------------------------

    def search(self, body: dict) -> dict:
        return self.msearch([body])[0]

    def msearch(self, bodies: list[dict],
                with_partials: bool = False) -> list[dict]:
        base_raw = self.base_searcher.raw_msearch(bodies)
        if self.tail_searcher is None:
            return [DistributedSearcher._build_response(b, [r])
                    for b, r in zip(bodies, base_raw)]
        tail_raw = self.tail_searcher.raw_msearch(bodies)
        return [DistributedSearcher._build_response(b, [rb, rt])
                for b, rb, rt in zip(bodies, base_raw, tail_raw)]
