"""Pod membership: quorum-fenced epochs + the coordinator lease.

Reference analog: zen2 (`cluster/coordination/Coordinator.java`). The
reference's cluster-state machine has three load-bearing invariants
this module reproduces over the mesh control plane:

  * **quorum-fenced publication** — a cluster-state change commits only
    when a majority of the LAST-KNOWN voting configuration acks it
    (`Publication.onPossibleCommitFailure`): after a partition, at most
    one half can contain a majority of the pre-partition members, so
    split-brain halves cannot both commit diverging membership. The
    minority half refuses the transition and keeps serving its last
    committed epoch (degraded, honest) until the partition heals and
    the majority's higher committed epoch syncs it forward.
  * **term-fenced leadership** — every coordinator holds a *term* and
    peers reject writes from older terms (`CoordinationState.
    handlePublishRequest` throws on stale terms). Here the term guards
    exec-seq minting: the lease holder is the ONE driver allowed to
    mint turns, a concurrent driver is fenced to a 409
    (`LeaseFencedError`) and re-acquires — replacing the PR 13
    "single driver at a time by convention" (and its residual seq
    collision window) with an enforced contract.
  * **leader failover** — a dead master's term expires and the
    best-informed survivor wins the next election (zen2 prefers nodes
    with the freshest cluster state). Here a vote is granted only to a
    candidate whose membership epoch is >= the voter's, so the lease
    lands on a highest-acked-epoch survivor and the coordination
    service is no longer a SPOF.

This module is the PURE layer: state machines + round orchestration
over an injected `submit(host, kind, payload) -> Future` callable —
no transport, no JAX, no global state. parallel/multihost.py maps the
round kinds onto its control-plane actions and owns the wire; tests
drive the machines single-process with fake clocks.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass

from ..utils.errors import LeaseFencedError


def quorum_size(n_members: int) -> int:
    """Majority of n: the smallest ack count two disjoint host sets
    cannot both reach (floor(n/2) + 1 — for n=2 that is 2: a 2-host
    pod cannot take quorum decisions with one side down, which is why
    quorum membership is OPT-IN and the 2-host eviction path keeps the
    health-threshold mode)."""
    if n_members <= 0:
        raise ValueError(f"quorum over {n_members} members")
    return n_members // 2 + 1


def has_quorum(n_acks: int, n_members: int) -> bool:
    return n_acks >= quorum_size(n_members)


@dataclass(frozen=True)
class MembershipRecord:
    """One committed membership generation: the epoch, the member set
    (ordered — host rows derive from the order), and each member's
    shard span (None span = replica layout, every member full)."""

    epoch: int
    members: tuple
    host_shards: dict | None = None


class PodLedger:
    """One host's replicated membership record + promise state.

    Two-phase, single-decree (the zen2 publish shape, not full
    multi-decree Paxos — membership transitions are rare and total-
    ordered by epoch): PROPOSE asks "may epoch E with members M
    commit?" and a host promises at most ONE proposal per epoch;
    COMMIT adopts the record once the proposer saw a quorum of
    promises. `promise` is the vote a minority partition side cannot
    collect a majority of; `commit` is monotonic in epoch, so a healed
    minority adopting the majority's record can never regress it."""

    def __init__(self, epoch: int, members, host_shards=None):
        self._mx = threading.Lock()
        self._committed = MembershipRecord(
            int(epoch), tuple(members),
            dict(host_shards) if host_shards is not None else None)
        self._promised_epoch = int(epoch)
        self._promised_to: str | None = None

    def committed(self) -> MembershipRecord:
        with self._mx:
            return self._committed

    def promise(self, epoch: int, proposer: str) -> tuple[bool, int]:
        """Vote on a proposed transition. Granted iff `epoch` is ahead
        of both the committed epoch and any prior promise (re-promise
        to the SAME proposer is idempotent — its retry must not fail
        its own round). Returns (granted, my committed epoch) — the
        epoch rides the refusal so a behind proposer can sync forward
        before retrying."""
        with self._mx:
            cur = self._committed.epoch
            if epoch <= cur:
                return False, cur
            if epoch < self._promised_epoch:
                return False, cur
            if epoch == self._promised_epoch \
                    and self._promised_to not in (None, proposer):
                return False, cur
            self._promised_epoch = epoch
            self._promised_to = proposer
            return True, cur

    def commit(self, epoch: int, members, host_shards=None) -> bool:
        """Adopt a committed record — monotonic: an older (or equal)
        epoch is a stale duplicate and is ignored. Returns True when
        the record newly committed (the caller rebuilds its view)."""
        with self._mx:
            if epoch <= self._committed.epoch:
                return False
            self._committed = MembershipRecord(
                int(epoch), tuple(members),
                dict(host_shards) if host_shards is not None else None)
            self._promised_epoch = max(self._promised_epoch, int(epoch))
            self._promised_to = None
            return True

    def snapshot(self) -> dict:
        with self._mx:
            rec = self._committed
            return {"epoch": rec.epoch, "members": list(rec.members),
                    "promised_epoch": self._promised_epoch}


class CoordinatorLease:
    """One host's view of the coordinator lease: (holder, term,
    expires_at on MY clock). Terms only move forward; expiry is judged
    per-voter on local monotonic clocks (the clock-sync table bounds
    cross-host skew for deadlines, but lease safety never depends on
    it — a too-early local expiry only costs an extra fenced retry,
    never a double-mint, because minting requires a quorum of votes).

    `clock` is injectable so the fast tier-1 legs drive expiry without
    sleeping."""

    def __init__(self, my_id: str, ttl_s: float, clock=time.monotonic):
        self.my_id = my_id
        self.ttl_s = float(ttl_s)
        self.clock = clock
        self._mx = threading.Lock()
        self._holder: str | None = None
        self._term = 0
        self._expires_at = 0.0

    # -- voter side --------------------------------------------------------

    def vote(self, candidate: str, term: int, candidate_epoch: int,
             my_epoch: int, handoff_from: str | None = None
             ) -> tuple[bool, dict]:
        """Grant iff ALL of:

          * `term` is ahead of every term I have seen (one vote per
            term — two same-term candidates split the electorate and
            at most one reaches quorum);
          * `candidate_epoch >= my_epoch` — failover lands on a
            highest-acked-epoch survivor; a candidate behind on
            membership syncs forward and retries;
          * the current lease is FREE for the taking: no holder, or
            expired at my clock, or the candidate already holds it
            (renewal), or the holder consented (`handoff_from` names
            it — the explicit release path; a voter that believes
            someone ELSE holds an unexpired lease refuses).

        A granted vote RECORDS the candidate as holder immediately
        (optimistic, like a Raft vote persisting votedFor): if the
        candidate loses the round the record expires on its own and
        costs nothing but one TTL of re-vote latency."""
        now = self.clock()
        with self._mx:
            if term <= self._term:
                return False, self._info_locked(now)
            if candidate_epoch < my_epoch:
                return False, self._info_locked(now)
            free = (self._holder is None
                    or now >= self._expires_at
                    or self._holder == candidate
                    or (handoff_from is not None
                        and handoff_from == self._holder))
            if not free:
                return False, self._info_locked(now)
            self._holder = candidate
            self._term = term
            self._expires_at = now + self.ttl_s
            return True, self._info_locked(now)

    def adopt(self, holder: str, term: int) -> bool:
        """Fold a lease observed on the wire (exec piggyback / join
        reply) in — forward-only in term, same monotonicity as epoch
        catch-up. An equal term from the SAME holder renews the
        expiry (each fenced exec is proof of life)."""
        with self._mx:
            if term < self._term:
                return False
            if term == self._term and holder != self._holder:
                return False
            self._holder = holder
            self._term = term
            self._expires_at = self.clock() + self.ttl_s
            return True

    def fence(self, holder: str, term: int) -> None:
        """The exec-time check: a turn minted under an older term than
        any this host has granted/adopted is a concurrent driver the
        electorate moved past — 409, never served. Current-or-newer
        terms are adopted (a voter that missed the round learns the
        result from the first fenced message)."""
        with self._mx:
            if term < self._term:
                raise LeaseFencedError(
                    f"exec under stale lease term {term} from "
                    f"[{holder}]: current term {self._term} held by "
                    f"[{self._holder}]",
                    term=self._term, holder=self._holder)
        self.adopt(holder, term)

    # -- holder side -------------------------------------------------------

    def i_hold(self) -> bool:
        now = self.clock()
        with self._mx:
            return (self._holder == self.my_id
                    and now < self._expires_at)

    def release(self) -> None:
        """Voluntary give-up (handoff grant): clear the holder so the
        next acquire round finds the lease free WITHOUT waiting out
        the TTL. Only meaningful on the holder; a non-holder calling
        it is a no-op."""
        with self._mx:
            if self._holder == self.my_id:
                self._holder = None
                self._expires_at = 0.0

    def term(self) -> int:
        with self._mx:
            return self._term

    def holder(self) -> tuple[str | None, int]:
        with self._mx:
            return self._holder, self._term

    def _info_locked(self, now: float) -> dict:
        return {"holder": self._holder, "term": self._term,
                "expired": now >= self._expires_at}

    def snapshot(self) -> dict:
        now = self.clock()
        with self._mx:
            return {"holder": self._holder, "term": self._term,
                    "held_by_me": (self._holder == self.my_id
                                   and now < self._expires_at),
                    "ttl_remaining_s": max(0.0, self._expires_at - now)}


class NoQuorumError(Exception):
    """A membership transition could not collect a majority of the
    last-known member set — the proposer is (at best) on the minority
    side of a partition and must NOT commit. Internal control-flow
    signal; multihost turns it into a decision-log entry + the
    partitions_survived counter, never a client error."""

    def __init__(self, msg: str, acks: int, needed: int):
        super().__init__(msg)
        self.acks = acks
        self.needed = needed


# round kinds PodCoordinator asks the injected submit() to carry;
# multihost maps each onto a MESH_* control-plane action
KIND_LEASE_VOTE = "lease_vote"
KIND_LEASE_RELEASE = "lease_release"
KIND_PROPOSE = "propose"
KIND_COMMIT = "commit"


class PodCoordinator:
    """Round orchestration over the two state machines. Holds NO lock
    across network waits: each round fans out through `submit(host,
    kind, payload) -> Future`, gathers outside every lock, then folds
    the verdict into the ledger/lease.

    `submit` is multihost's fault-hooked control-plane sender;
    `peers()` returns the hosts a round should cover (committed
    members minus self — dead ones simply fail their Future and count
    as nacks). Vote counts always include self. `on_peer_error(host,
    exc)` (optional) sees every peer whose round leg failed
    OUTRIGHT — multihost feeds it to the health tracker so a dead
    voter's nacks drive eviction the same way dead exec peers do
    (without it, a fenced election would starve failure detection)."""

    def __init__(self, my_id: str, ledger: PodLedger,
                 lease: CoordinatorLease, submit, peers,
                 round_timeout_s: float = 5.0, on_peer_error=None):
        self.my_id = my_id
        self.ledger = ledger
        self.lease = lease
        self._submit = submit
        self._peers = peers
        self._on_peer_error = on_peer_error
        self.round_timeout_s = float(round_timeout_s)

    def _gather(self, kind: str, payload: dict,
                hosts=None) -> dict[str, dict | Exception]:
        hosts = list(self._peers() if hosts is None else hosts)
        futs = {}
        for h in hosts:
            if h == self.my_id:
                continue
            try:
                futs[h] = self._submit(h, kind, payload)
            except Exception as e:  # noqa: BLE001 — a nack, not fatal
                futs[h] = e
        out: dict[str, dict | Exception] = {}
        for h, f in futs.items():
            if isinstance(f, Exception):
                out[h] = f
            else:
                try:
                    out[h] = f.result(timeout=self.round_timeout_s)
                except Exception as e:  # noqa: BLE001
                    out[h] = e
            if isinstance(out[h], Exception) \
                    and self._on_peer_error is not None:
                try:
                    self._on_peer_error(h, out[h])
                except Exception:  # noqa: BLE001 — observer only
                    pass
        return out

    # -- lease rounds ------------------------------------------------------

    def acquire_lease(self, my_epoch: int,
                      handoff_from: str | None = None) -> int:
        """One election round: bump past every term I know, fan the
        vote out, win on a majority of the CURRENT committed member
        set (self-vote included). Returns the won term; raises
        LeaseFencedError when the electorate said no (caller backs
        off/hands off and retries — the 409 contract)."""
        members = self.ledger.committed().members
        holder, _t = self.lease.holder()
        if handoff_from is None and holder is not None \
                and holder != self.my_id and holder not in members:
            # the recorded holder was EVICTED from the committed set:
            # that quorum decision vacates the lease (an evicted host
            # cannot mint — every peer fences its epoch) — treat it as
            # the holder's consent instead of waiting out the TTL
            handoff_from = holder
        term = self.lease.term() + 1
        payload = {"candidate": self.my_id, "term": term,
                   "epoch": my_epoch, "handoff_from": handoff_from}
        ok, _ = self.lease.vote(self.my_id, term, my_epoch, my_epoch,
                                handoff_from=handoff_from)
        acks = 1 if ok else 0
        best: dict | None = None
        for h, r in self._gather(KIND_LEASE_VOTE, payload,
                                 hosts=members).items():
            if isinstance(r, Exception) or not isinstance(r, dict):
                continue
            if r.get("granted"):
                acks += 1
            else:
                info = r.get("lease") or {}
                if best is None or info.get("term", 0) > best.get(
                        "term", 0):
                    best = info
        if not ok or not has_quorum(acks, len(members)):
            if best and best.get("term"):
                # learn the refusing electorate's term so the next
                # round bumps past it instead of re-losing
                self.lease.adopt(best.get("holder") or "?",
                                 int(best["term"]))
            raise LeaseFencedError(
                f"lease acquire for [{self.my_id}] term {term} got "
                f"{acks}/{quorum_size(len(members))} votes",
                term=term, holder=(best or {}).get("holder"))
        return term

    def request_handoff(self, holder: str) -> bool:
        """Ask the current holder to release (the fast path a second
        driver takes instead of waiting a TTL out). The holder grants
        iff idle; an unreachable/crashed holder is a refusal — expiry
        failover covers that arc."""
        r = self._gather(KIND_LEASE_RELEASE,
                         {"candidate": self.my_id}, hosts=[holder]
                         ).get(holder)
        return isinstance(r, dict) and bool(r.get("granted"))

    # -- membership rounds -------------------------------------------------

    def propose_transition(self, members, host_shards, reason: str,
                           extra: dict | None = None) -> int:
        """Two-phase membership change. The quorum is judged against
        the LAST-KNOWN committed member set — the electorate that must
        not fork — never against the proposed one (electing yourself
        into a majority is the classic split-brain bug). Commit is
        best-effort fan-out to the union of old and new members:
        anyone missed learns from epoch catch-up on the next message.
        `extra` rides the commit payload (the join handshake ships the
        joiner's pack summary and address through it). Returns the
        committed epoch; raises NoQuorumError with the transition
        UNCOMMITTED otherwise."""
        cur = self.ledger.committed()
        epoch = cur.epoch + 1
        payload = {"epoch": epoch, "members": list(members),
                   "proposer": self.my_id, "reason": reason}
        ok, _ = self.ledger.promise(epoch, self.my_id)
        acks = 1 if ok else 0
        for h, r in self._gather(KIND_PROPOSE, payload,
                                 hosts=cur.members).items():
            if isinstance(r, dict) and r.get("promised"):
                acks += 1
        needed = quorum_size(len(cur.members))
        if not ok or acks < needed:
            raise NoQuorumError(
                f"membership transition to epoch {epoch} ({reason}) "
                f"got {acks}/{needed} promises from "
                f"{list(cur.members)}", acks=acks, needed=needed)
        self.ledger.commit(epoch, members, host_shards)
        commit_payload = {"epoch": epoch, "members": list(members),
                          "host_shards": host_shards,
                          "proposer": self.my_id, "reason": reason,
                          **(extra or {})}
        fan = set(cur.members) | set(members)
        self._gather(KIND_COMMIT, commit_payload, hosts=sorted(fan))
        return epoch
