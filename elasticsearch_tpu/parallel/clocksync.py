"""Cross-host monotonic clock-offset estimation for preemptive deadlines.

Reference analog: the reference never compares wall clocks across nodes
— its fault detector (discovery/zen/fd/NodesFaultDetection.java) and
its search timeouts are all LOCAL decisions. The TPU mesh cannot afford
that luxury for the STEPPED deadline (PR 8): the device-side verdict
polls `time.monotonic()` inside an io_callback on every process, and a
deadline minted on the driving host's monotonic clock is meaningless on
a peer — Python's monotonic epoch is per-process (usually boot time,
but pinned to nothing across machines).

So the mesh runs the classic symmetric round-trip estimate (NTP's
clock-filter algorithm reduced to its core, à la Cristian):

    t0 = my_clock()                 # request leaves
    t  = peer_clock()               # peer timestamps service
    t1 = my_clock()                 # response arrives

    offset(peer - me) ≈ t - (t0 + t1) / 2
    uncertainty        = (t1 - t0) / 2     (+ a floor)

The midpoint estimate is exact when the outbound and return legs are
symmetric; asymmetry is bounded by half the round trip, which is what
`uncertainty` carries. Repeated samples keep the MINIMUM-RTT one — the
sample least polluted by queueing delay (NTP's clock filter does the
same). Age inflates the bound by a drift allowance (crystal oscillators
drift; 100 ppm is a conservative ceiling for commodity parts), so a
stale handshake degrades honestly instead of silently lying.

`correct_deadline` then maps a driver-clock deadline onto a peer's
clock CONSERVATIVELY: the pad pushes the local deadline LATER, so a
peer can never preempt before the driver's true cutoff — a cross-host
stepped search 504s within (deadline + pad), never early.

Pure math + a small locked table; the transport round trips live in
parallel/multihost.py (MESH_CLOCK_ACTION).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass

# drift allowance between re-syncs: bound on |d(offset)/dt| for
# commodity crystal oscillators (real parts sit well under 50 ppm;
# doubled for headroom). At the default 30 s resync cadence this adds
# 3 ms to the pad — noise next to a search deadline.
DRIFT_PPM = 100.0

# uncertainty floor: a same-process round trip can measure ~0 RTT,
# but scheduler jitter between the clock reads is real
MIN_UNCERTAINTY_S = 0.0005


@dataclass(frozen=True)
class ClockSample:
    """One round trip: (my send time, peer service time, my recv time),
    all raw monotonic readings."""

    t0: float
    t_peer: float
    t1: float

    @property
    def rtt(self) -> float:
        return max(0.0, self.t1 - self.t0)

    @property
    def offset(self) -> float:
        """Midpoint estimate of (peer clock - my clock)."""
        return self.t_peer - (self.t0 + self.t1) / 2.0

    @property
    def uncertainty(self) -> float:
        """Half the round trip: the worst-case asymmetry error."""
        return max(self.rtt / 2.0, MIN_UNCERTAINTY_S)


@dataclass(frozen=True)
class ClockOffset:
    """The adopted estimate for one peer: offset = (peer - me) seconds
    on the monotonic clocks, `uncertainty` the sample bound at
    `measured_at` (MY clock)."""

    offset: float
    uncertainty: float
    measured_at: float

    def pad(self, now: float) -> float:
        """Conservative one-sided error bound at `now`: the sample
        bound plus drift accumulated since measurement."""
        age = max(0.0, now - self.measured_at)
        return self.uncertainty + age * (DRIFT_PPM * 1e-6)

    def compose(self, other: "ClockOffset") -> "ClockOffset":
        """Transitive estimate: given self = (A - me) and other =
        (B - A), return (B - me). Offsets add; uncertainties add too
        (both legs' asymmetry errors are independent and one-sided
        bounds compose by sum — wider, never wrong). `measured_at`
        takes the OLDER leg's timestamp so drift accrues from the
        stalest link in the chain.

        The join handshake uses this to SEED a fresh process's clock
        table from one survivor's snapshot (survivor knows every peer;
        the joiner knows only the survivor) — direct handshakes then
        tighten each entry because ClockTable.record keeps the tighter
        estimate. Note the seed-side caveat: a peer's `measured_at`
        lives on the PEER's clock, so the survivor re-stamps entries
        with its pad folded in before sending (see
        MultiHostIndex._on_join)."""
        return ClockOffset(
            offset=self.offset + other.offset,
            uncertainty=self.uncertainty + other.uncertainty,
            measured_at=min(self.measured_at, other.measured_at))


def estimate_offset(samples: list[ClockSample]) -> ClockOffset:
    """Adopt the minimum-RTT sample (NTP clock filter): queueing delay
    only ever widens a round trip, so the tightest sample carries the
    least asymmetry error."""
    if not samples:
        raise ValueError("cannot estimate a clock offset from 0 samples")
    best = min(samples, key=lambda s: s.rtt)
    return ClockOffset(offset=best.offset,
                       uncertainty=best.uncertainty,
                       measured_at=best.t1)


def correct_deadline(deadline_remote: float, off: ClockOffset,
                     now: float | None = None) -> float:
    """Map an absolute deadline on the REMOTE (driver) clock onto the
    local clock, padded so the local cutoff is never EARLIER than the
    remote one truly is: remote clock reads r when mine reads
    r - offset, and the estimate may be wrong by ±pad, so the safe
    local deadline is (deadline - offset) + pad."""
    if now is None:
        now = time.monotonic()
    return deadline_remote - off.offset + off.pad(now)


class ClockTable:
    """Per-peer offset estimates, refreshed by handshake round trips
    and by every successful heartbeat (each ping is a free sample)."""

    def __init__(self, clock=time.monotonic):
        self.clock = clock
        self._mx = threading.Lock()
        self._offsets: dict[str, ClockOffset] = {}

    def record(self, host: str, sample: ClockSample) -> ClockOffset:
        """Fold one round trip in: adopt it when it is tighter (at its
        age) than what drift has left of the current estimate."""
        cand = ClockOffset(sample.offset, sample.uncertainty, sample.t1)
        with self._mx:
            cur = self._offsets.get(host)
            if cur is None or cand.pad(sample.t1) <= cur.pad(sample.t1):
                self._offsets[host] = cand
                return cand
            return cur

    def seed(self, host: str, off: ClockOffset) -> ClockOffset:
        """Fold a pre-composed estimate in (a joiner seeding its table
        transitively from a survivor's links — ClockOffset.compose),
        same keep-tighter rule as record(): a later direct handshake
        with a smaller pad replaces the seed, a wider one never
        loosens it."""
        now = self.clock()
        with self._mx:
            cur = self._offsets.get(host)
            if cur is None or off.pad(now) <= cur.pad(now):
                self._offsets[host] = off
                return off
            return cur

    def get(self, host: str) -> ClockOffset | None:
        with self._mx:
            return self._offsets.get(host)

    def forget(self, host: str) -> None:
        """Eviction hook: a rejoining host re-handshakes from scratch
        (its process may have restarted — a fresh monotonic epoch)."""
        with self._mx:
            self._offsets.pop(host, None)

    def fresh(self, hosts, max_uncertainty_s: float) -> bool:
        """Are ALL the given peers' estimates currently tighter than
        `max_uncertainty_s`? The driver's go/no-go for arming the
        cross-host stepped deadline — a stale or missing estimate
        drops the mesh back to cooperative timeouts, never to a wrong
        preemption."""
        now = self.clock()
        with self._mx:
            for h in hosts:
                off = self._offsets.get(h)
                if off is None or off.pad(now) > max_uncertainty_s:
                    return False
        return True

    def snapshot(self) -> dict:
        with self._mx:
            offs = dict(self._offsets)
        now = self.clock()
        return {h: {"offset_ms": off.offset * 1000.0,
                    "uncertainty_ms": off.uncertainty * 1000.0,
                    "pad_ms": off.pad(now) * 1000.0,
                    "age_s": max(0.0, now - off.measured_at)}
                for h, off in offs.items()}
