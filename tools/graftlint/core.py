"""graftlint core: package model, suppressions, traced-context analysis.

The analyzer is a plain-AST whole-package pass (no imports of the
analyzed code, so it runs in milliseconds and can never be broken by a
missing accelerator): every module is parsed once into a `Module` fact
table (functions, jit wrappers, locks, imports), the `Package` index
resolves cross-module calls by name, and each rule family walks those
facts. Precision follows the codebase's own conventions — pow2
bucketing via `next_pow2`, `utils/breaker.Hold` reservations with the
`_gc_backstop` weakref finalizer, the io_callback step poll — which
are recognized structurally rather than special-cased by file.

Suppression syntax (reason is MANDATORY):

    some_call()  # graftlint: ok(rule-name): why this is safe

either on the flagged line or alone on the line directly above it. A
reason-less `ok(...)` is itself a finding (`bad-suppression`), and a
suppression that silences nothing is flagged `unused-suppression` so
stale annotations cannot rot in place. A suppression on a lock's
definition line exempts that lock from the blocking-call rule (a
declared serialization latch) and is never counted unused.
"""

from __future__ import annotations

import ast
import io
import json
import os
import re
import tokenize
from dataclasses import dataclass, field

RULES = (
    "breaker-hold",      # every add_estimate needs a release on all exits
    "trace-purity",      # no host syncs / side effects inside traced code
    "donation-safety",   # donated buffers are dead after the donating call
    "recompile-hazard",  # unhashable/request-varying statics, unbucketed k
    "lock-discipline",   # no blocking calls under hot-path locks
    "lock-order",        # lock acquisition-order graph must be acyclic
    "shared-state-race",   # cross-thread state needs a common lockset
    "collective-safety",   # SPMD collectives: no divergence, bound axes
    "bad-suppression",   # ok(...) without a reason
    "unused-suppression",  # ok(...) that silences nothing
)

_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*ok\(\s*([a-z0-9_,\s-]+)\s*\)\s*(?::\s*(.*\S))?")


@dataclass
class Finding:
    rule: str
    path: str
    line: int
    col: int
    message: str
    suppressed: bool = False
    reason: str | None = None

    def key(self) -> str:
        """Baseline fingerprint: stable across unrelated edits only as
        far as the line number — the baseline is meant to stay EMPTY,
        so cheap beats churn-proof."""
        return f"{self.path}:{self.rule}:{self.line}"

    def render(self) -> str:
        tag = " [suppressed: %s]" % self.reason if self.suppressed else ""
        return f"{self.path}:{self.line}:{self.col}: {self.rule}: " \
               f"{self.message}{tag}"


@dataclass
class Suppression:
    line: int
    rules: tuple[str, ...]
    reason: str | None
    used: bool = False
    lock_def: bool = False  # sits on a lock definition line


@dataclass
class LockInfo:
    key: str                  # "module.Class.attr" or "module.name"
    module: "Module"
    def_line: int
    exempt: bool = False      # definition-site ok(lock-discipline)


@dataclass
class JitInfo:
    name: str
    static_argnames: tuple[str, ...] = ()
    donate_argnums: tuple[int, ...] = ()


@dataclass
class FuncInfo:
    module: "Module"
    node: ast.FunctionDef
    qualname: str
    class_name: str | None
    parent: "FuncInfo | None" = None
    nested: "list[FuncInfo]" = field(default_factory=list)

    @property
    def name(self) -> str:
        return self.node.name

    def params(self) -> list[str]:
        a = self.node.args
        return ([p.arg for p in a.posonlyargs] + [p.arg for p in a.args]
                + [p.arg for p in a.kwonlyargs])


def call_name(call: ast.Call) -> str:
    """Dotted textual name of a call target ('' when not name-shaped)."""
    return dotted(call.func)


def dotted(node: ast.AST) -> str:
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    elif isinstance(node, ast.Call):
        inner = dotted(node.func)
        parts.append(f"{inner}()" if inner else "()")
    else:
        return ""
    return ".".join(reversed(parts))


def _partial_target(call: ast.Call):
    """partial(f, ...) / functools.partial(f, ...) -> the f node."""
    if call_name(call).split(".")[-1] == "partial" and call.args:
        return call.args[0]
    return None


def _jit_keywords(keywords: list[ast.keyword], name: str) -> JitInfo:
    """static_argnames/donate_argnums extraction shared by the plain
    jit call form and the partial(jax.jit, ...) decorator form."""
    statics: tuple[str, ...] = ()
    donate: tuple[int, ...] = ()
    for kw in keywords:
        if kw.arg == "static_argnames":
            statics = tuple(
                n.value for n in ast.walk(kw.value)
                if isinstance(n, ast.Constant) and isinstance(n.value, str))
        elif kw.arg == "donate_argnums":
            donate = tuple(
                n.value for n in ast.walk(kw.value)
                if isinstance(n, ast.Constant) and isinstance(n.value, int))
    return JitInfo(name, statics, donate)


def _jit_call_info(call: ast.Call) -> JitInfo | None:
    """jax.jit(...) / pjit(...) call -> static/donate extraction."""
    base = call_name(call).split(".")[-1]
    if base not in ("jit", "pjit"):
        return None
    return _jit_keywords(call.keywords, "")


class Module:
    """Per-file fact table (pure syntax, no imports executed)."""

    def __init__(self, path: str, relpath: str, source: str,
                 snippet: bool = False):
        self.path = path
        self.relpath = relpath
        self.source = source
        self.snippet = snippet
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=relpath)
        self.functions: list[FuncInfo] = []
        # bare name -> FuncInfo list (methods and module functions alike)
        self.by_name: dict[str, list[FuncInfo]] = {}
        self.jit: dict[str, JitInfo] = {}        # func name -> jit facts
        self.locks: dict[str, LockInfo] = {}     # lock key suffix -> info
        self.imports: dict[str, str] = {}        # local name -> module tail
        self.suppressions: dict[int, Suppression] = {}
        self.parse_findings: list[Finding] = []
        self._collect_suppressions()
        self._collect_functions()
        self._collect_jit()
        self._collect_locks()
        self._collect_imports()

    # -- harvest ----------------------------------------------------------
    def _collect_suppressions(self) -> None:
        try:
            toks = tokenize.generate_tokens(io.StringIO(self.source).readline)
            comments = [(t.start[0], t.string) for t in toks
                        if t.type == tokenize.COMMENT]
        except tokenize.TokenError:
            comments = []
        for line, text in comments:
            m = _SUPPRESS_RE.search(text)
            if not m:
                if "graftlint" in text and "ok(" in text:
                    self.parse_findings.append(Finding(
                        "bad-suppression", self.relpath, line, 0,
                        f"unparseable graftlint comment: {text.strip()!r}"))
                continue
            rules = tuple(r.strip() for r in m.group(1).split(",") if r.strip())
            reason = m.group(2)
            unknown = [r for r in rules if r not in RULES]
            if unknown:
                self.parse_findings.append(Finding(
                    "bad-suppression", self.relpath, line, 0,
                    f"unknown rule(s) {unknown} in suppression"))
                # don't also register it: a typo'd rule can never match
                # a finding, and reporting the same line a second time
                # as unused-suppression doubles one authoring mistake
                continue
            if not reason:
                self.parse_findings.append(Finding(
                    "bad-suppression", self.relpath, line, 0,
                    "suppression without a reason — write "
                    "`# graftlint: ok(rule): why`"))
                continue
            self.suppressions[line] = Suppression(line, rules, reason)

    def _collect_functions(self) -> None:
        def visit(node, class_name, parent):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    qual = ".".join(x for x in (class_name, child.name) if x)
                    fi = FuncInfo(self, child, qual, class_name, parent)
                    self.functions.append(fi)
                    self.by_name.setdefault(child.name, []).append(fi)
                    if parent is not None:
                        parent.nested.append(fi)
                    visit(child, class_name, fi)
                elif isinstance(child, ast.ClassDef):
                    visit(child, child.name, None)
                else:
                    visit(child, class_name, parent)
        visit(self.tree, None, None)

    def _collect_jit(self) -> None:
        for fi in self.functions:
            for dec in fi.node.decorator_list:
                info = None
                name = dotted(dec).split(".")[-1] if not isinstance(
                    dec, ast.Call) else None
                if name in ("jit", "pjit"):
                    info = JitInfo(fi.name)
                elif isinstance(dec, ast.Call):
                    target = _partial_target(dec)
                    if target is not None and \
                            dotted(target).split(".")[-1] in ("jit", "pjit"):
                        info = _jit_call_info_from_partial(dec, fi.name)
                    else:
                        info = _jit_call_info(dec)
                        if info is not None:
                            info = JitInfo(fi.name, info.static_argnames,
                                           info.donate_argnums)
                if info is not None:
                    self.jit[fi.name] = info
        # assignment form: g = jax.jit(f, static_argnames=..., ...)
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Assign) and isinstance(node.value,
                                                           ast.Call):
                info = _jit_call_info(node.value)
                if info is None:
                    continue
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self.jit[t.id] = JitInfo(t.id, info.static_argnames,
                                                 info.donate_argnums)

    def _collect_locks(self) -> None:
        mod = os.path.splitext(os.path.basename(self.relpath))[0]
        for node in ast.walk(self.tree):
            if not (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)):
                continue
            base = call_name(node.value).split(".")[-1]
            if base not in ("Lock", "RLock", "Condition"):
                continue
            for t in node.targets:
                if isinstance(t, ast.Name):
                    key = f"{mod}.{t.id}"
                    suffix = t.id
                elif isinstance(t, ast.Attribute) and \
                        isinstance(t.value, ast.Name) and t.value.id == "self":
                    cls = self._enclosing_class(node)
                    key = f"{mod}.{cls}.{t.attr}"
                    suffix = t.attr
                else:
                    continue
                li = LockInfo(key, self, node.lineno)
                sup = self.suppression_for(node.lineno, "lock-discipline")
                if sup is not None:
                    li.exempt = True
                    sup.lock_def = True
                    sup.used = True
                self.locks[suffix] = li

    def suppression_for(self, line: int, rule: str) -> Suppression | None:
        """Suppression covering `line`: on the line itself, or in the
        contiguous comment block directly above it (a reason often
        wraps over several comment lines)."""
        sup = self.suppressions.get(line)
        if sup and rule in sup.rules:
            return sup
        ln = line - 1
        while ln > 0:
            text = self.lines[ln - 1].strip() if ln <= len(self.lines) else ""
            if not text.startswith("#"):
                return None      # code or blank line breaks the block
            sup = self.suppressions.get(ln)
            if sup and rule in sup.rules:
                return sup
            ln -= 1
        return None

    def _enclosing_class(self, node) -> str:
        for fi in self.functions:
            if fi.class_name and node in ast.walk(fi.node):
                return fi.class_name
        return "?"

    def _collect_imports(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.ImportFrom) and node.module:
                for alias in node.names:
                    self.imports[alias.asname or alias.name] = \
                        node.module.rsplit(".", 1)[-1]


def _jit_call_info_from_partial(dec: ast.Call, fname: str) -> JitInfo:
    return _jit_keywords(dec.keywords, fname)


# ---------------------------------------------------------------------------
# Package index
# ---------------------------------------------------------------------------

# names whose positional argument N is traced as a program body
_TRACE_ENTRY_ARGS = {
    "fori_loop": (2,), "while_loop": (0, 1), "scan": (0,), "map": (0,),
    "cond": (1, 2), "switch": (1,), "pallas_call": (0,), "shard_map": (0,),
    "vmap": (0,), "grad": (0,), "value_and_grad": (0,), "jit": (0,),
    "pjit": (0,), "eval_shape": (0,), "checkpoint": (0,), "remat": (0,),
}
# the sanctioned device->host bridge: functions handed to these run on
# the HOST and are exempt from trace purity
_HOST_CALLBACK_ENTRIES = ("io_callback", "pure_callback", "callback",
                          "debug_callback")

# names whose positional argument N runs on ANOTHER THREAD: thread-pool
# submit/execute, Timer bodies, weakref.finalize callbacks (the GC
# thread), and the io_callback host halves (jax's callback thread).
# threading.Thread itself publishes its target via the `target=`
# keyword and is handled separately.
_THREAD_ENTRY_ARGS = {
    "submit": (0,), "execute": (0,), "finalize": (1,), "Timer": (1,),
    "call_soon_threadsafe": (0,), "run_in_executor": (1,),
}


class Package:
    """Whole-package view + cross-module name resolution."""

    def __init__(self, modules: list[Module]):
        self.modules = modules
        self._global: dict[str, list[FuncInfo]] = {}
        for m in modules:
            for name, fis in m.by_name.items():
                self._global.setdefault(name, []).extend(fis)
        self._traced: dict[int, tuple[FuncInfo, str]] | None = None
        self._callback_ids: set[int] | None = None
        self._thread_entries: dict[int, tuple[FuncInfo, str]] | None = None

    # -- resolution -------------------------------------------------------
    def resolve(self, module: Module, name: str,
                from_func: FuncInfo | None = None) -> FuncInfo | None:
        """Bare name -> FuncInfo: nested defs first, then the caller's
        class, then the module, then one package-wide unique match
        (imports are not chased precisely; a unique name is enough)."""
        bare = name.split(".")[-1]
        if from_func is not None:
            for fi in from_func.nested:
                if fi.name == bare:
                    return fi
            if name.startswith("self.") and from_func.class_name:
                for fi in module.by_name.get(bare, []):
                    if fi.class_name == from_func.class_name:
                        return fi
        for fi in module.by_name.get(bare, []):
            if fi.class_name is None:
                return fi
        hits = [fi for fi in self._global.get(bare, [])
                if fi.class_name is None]
        if len(hits) == 1:
            return hits[0]
        return None

    def jit_info(self, module: Module, name: str) -> JitInfo | None:
        bare = name.split(".")[-1]
        if bare in module.jit:
            return module.jit[bare]
        hits = [m.jit[bare] for m in self.modules if bare in m.jit]
        if len(hits) == 1:
            return hits[0]
        return None

    def call_sites(self, func: FuncInfo) -> list[tuple[FuncInfo, ast.Call]]:
        """Every call to `func` by bare name across the package."""
        out = []
        for m in self.modules:
            for caller in m.functions:
                for call in calls_in(caller.node):
                    if call_name(call).split(".")[-1] == func.name:
                        out.append((caller, call))
        return out

    # -- traced-context computation ---------------------------------------
    def host_callback_ids(self) -> set[int]:
        """id() of FunctionDef nodes handed to io_callback & friends —
        they are HOST halves regardless of where they are referenced."""
        if self._callback_ids is not None:
            return self._callback_ids
        ids: set[int] = set()
        for m in self.modules:
            for fi in m.functions:
                for call in calls_in(fi.node):
                    if call_name(call).split(".")[-1] not in \
                            _HOST_CALLBACK_ENTRIES:
                        continue
                    if not call.args:
                        continue
                    target = self._arg_func(m, fi, call.args[0])
                    if target is not None:
                        ids.add(id(target.node))
        self._callback_ids = ids
        return ids

    def _arg_func(self, module: Module, fi: FuncInfo,
                  arg: ast.AST) -> FuncInfo | None:
        if isinstance(arg, ast.Call):
            inner = _partial_target(arg)
            if inner is not None:
                arg = inner
        name = dotted(arg)
        if not name:
            return None
        return self.resolve(module, name, fi)

    def thread_entries(self) -> dict[int, tuple[FuncInfo, str]]:
        """id(FunctionDef) -> (FuncInfo, why) for every function that
        runs on a thread OTHER than its caller's: `threading.Thread(
        target=f)`, thread-pool `.submit(f)`/`.execute(f)`, `Timer`
        bodies, `weakref.finalize(obj, f)` callbacks, and io_callback
        host halves. The shared-state-race pass uses this to decide
        which module globals are genuinely cross-thread."""
        if self._thread_entries is not None:
            return self._thread_entries
        entries: dict[int, tuple[FuncInfo, str]] = {}

        def add(target: ast.AST, m: Module, fi: FuncInfo,
                why: str) -> None:
            t = self._arg_func(m, fi, target)
            if t is not None and id(t.node) not in entries:
                entries[id(t.node)] = (t, why)

        for m in self.modules:
            for fi in m.functions:
                for call in calls_in(fi.node):
                    base = call_name(call).split(".")[-1]
                    if base in ("Thread", "Timer"):
                        for kw in call.keywords:
                            if kw.arg == "target":
                                add(kw.value, m, fi,
                                    f"Thread target (via {fi.qualname})")
                    idxs = _THREAD_ENTRY_ARGS.get(base)
                    if idxs:
                        for i in idxs:
                            if i < len(call.args):
                                add(call.args[i], m, fi,
                                    f"{base}() entry (via {fi.qualname})")
        for m in self.modules:
            for fi in m.functions:
                if id(fi.node) in self.host_callback_ids() and \
                        id(fi.node) not in entries:
                    entries[id(fi.node)] = (fi, "io_callback host half")
        self._thread_entries = entries
        return entries

    def traced(self) -> dict[int, tuple[FuncInfo, str]]:
        """id(FunctionDef) -> (FuncInfo, why-traced). Seeds: jit
        decorations and bodies handed to lax control flow / pallas /
        shard_map; closure: nested defs and package-resolvable callees
        of traced functions, minus host-callback halves."""
        if self._traced is not None:
            return self._traced
        cb = self.host_callback_ids()
        traced: dict[int, tuple[FuncInfo, str]] = {}

        def memoized(fi: FuncInfo) -> bool:
            """lru_cache'd helpers are deterministic per key — a traced
            body calling one reads frozen host config, not live state —
            so they stop the traced-propagation front."""
            return any(dotted(d).split(".")[-1] in ("lru_cache", "cache")
                       or (isinstance(d, ast.Call)
                           and dotted(d.func).split(".")[-1]
                           in ("lru_cache", "cache"))
                       for d in fi.node.decorator_list)

        def add(fi: FuncInfo, why: str) -> bool:
            if id(fi.node) in cb or id(fi.node) in traced or memoized(fi):
                return False
            traced[id(fi.node)] = (fi, why)
            return True

        for m in self.modules:
            for fi in m.functions:
                if fi.name in m.jit:
                    add(fi, f"@jit {fi.qualname}")
                # decorator form `@partial(shard_map, mesh=..., ...)` /
                # `@partial(pmap, ...)`: the decorated function IS the
                # mesh program body (PR 8's stepped mesh program uses
                # exactly this shape) — jit partials are collected into
                # m.jit already, so only the mesh entries need seeding
                for dec in fi.node.decorator_list:
                    if isinstance(dec, ast.Call):
                        target = _partial_target(dec)
                        if target is not None and dotted(target).split(
                                ".")[-1] in ("shard_map", "pmap",
                                             "xmap"):
                            add(fi, f"shard_map body {fi.qualname}")
            for fi in m.functions:
                for call in calls_in(fi.node):
                    base = call_name(call).split(".")[-1]
                    idxs = _TRACE_ENTRY_ARGS.get(base)
                    if not idxs:
                        continue
                    for i in idxs:
                        if i < len(call.args):
                            t = self._arg_func(m, fi, call.args[i])
                            if t is not None:
                                add(t, f"body of {base} "
                                       f"(via {fi.qualname})")
        # fixpoint: callees of traced functions are traced
        changed = True
        while changed:
            changed = False
            for fi, why in list(traced.values()):
                for sub in fi.nested:
                    if add(sub, f"nested in traced {fi.qualname}"):
                        changed = True
                for call in calls_in(fi.node, skip_nested=True):
                    name = call_name(call)
                    if not name:
                        continue
                    t = self.resolve(fi.module, name, fi)
                    if t is None:
                        continue
                    # same-module callees always propagate; cross-module
                    # only through an actual import of the name (a
                    # coincidental unique bare name must not taint)
                    if t.module is fi.module or \
                            name.split(".")[0] in fi.module.imports:
                        if add(t, f"called from traced {fi.qualname}"):
                            changed = True
        self._traced = traced
        return traced


def calls_in(node: ast.AST, skip_nested: bool = False) -> list[ast.Call]:
    out = []
    stack = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        if skip_nested and isinstance(n, (ast.FunctionDef,
                                          ast.AsyncFunctionDef)):
            continue
        if isinstance(n, ast.Call):
            out.append(n)
        stack.extend(ast.iter_child_nodes(n))
    return out


# ---------------------------------------------------------------------------
# Loading + suppression application
# ---------------------------------------------------------------------------

def load_package(root: str, package: str) -> Package:
    """Parse every .py under `root/package` into the fact index."""
    modules = []
    pkg_dir = os.path.join(root, package)
    for dirpath, _dirs, files in os.walk(pkg_dir):
        for fn in sorted(files):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, root)
            with open(path, encoding="utf-8") as f:
                src = f.read()
            modules.append(Module(path, rel, src))
    return Package(modules)


def load_source(source: str, relpath: str = "<snippet>.py") -> Package:
    """Single-snippet package (the test-fixture entry). Snippet modules
    count as hot-path for the lock-discipline scope."""
    return Package([Module(relpath, relpath, source, snippet=True)])


def apply_suppressions(pkg: Package,
                       findings: list[Finding]) -> list[Finding]:
    """Mark findings suppressed by a same-line / line-above ok(...);
    then surface bad + unused suppressions as findings themselves."""
    by_file = {m.relpath: m for m in pkg.modules}
    for f in findings:
        m = by_file.get(f.path)
        if m is None:
            continue
        sup = m.suppression_for(f.line, f.rule)
        if sup is not None:
            f.suppressed = True
            f.reason = sup.reason
            sup.used = True
    out = list(findings)
    for m in pkg.modules:
        out.extend(m.parse_findings)
        for sup in m.suppressions.values():
            if not sup.used and not sup.lock_def:
                out.append(Finding(
                    "unused-suppression", m.relpath, sup.line, 0,
                    f"suppression ok({', '.join(sup.rules)}) silences "
                    f"nothing — remove it or fix the rule name"))
    out.sort(key=lambda f: (f.path, f.line, f.rule))
    return out


def load_baseline(path: str) -> set[str]:
    try:
        with open(path, encoding="utf-8") as f:
            return set(json.load(f))
    except (OSError, ValueError):
        return set()


def rule_counts(findings: list[Finding]) -> dict[str, int]:
    """Per-rule firing counts INCLUDING suppressed hits — the CI diff
    surface: a new suppression moves a number, not just a scroll."""
    counts = {r: 0 for r in RULES}
    for f in findings:
        counts[f.rule] = counts.get(f.rule, 0) + 1
    return counts
