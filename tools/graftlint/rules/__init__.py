"""Rule registry: one module per family, each exposing check(pkg)."""

from . import (breaker_rules, donation_rules, lock_rules, recompile_rules,
               trace_rules)

ALL_RULES = (
    breaker_rules.check,
    trace_rules.check,
    donation_rules.check,
    recompile_rules.check,
    lock_rules.check,
)
