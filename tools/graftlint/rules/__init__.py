"""Rule registry: one module per family, each exposing check(pkg)."""

from . import (breaker_rules, collective_rules, donation_rules,
               lock_rules, recompile_rules, shared_state_rules,
               trace_rules)

ALL_RULES = (
    breaker_rules.check,
    trace_rules.check,
    donation_rules.check,
    recompile_rules.check,
    lock_rules.check,
    shared_state_rules.check,
    collective_rules.check,
)
