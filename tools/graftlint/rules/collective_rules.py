"""Rule family 7 — SPMD collective safety.

Every device in a mesh program must issue the SAME sequence of
collectives, or the mesh deadlocks (each device parks in a reduce the
others never enter) — the failure mode ROADMAP item 6's cross-host
stepped deadlines must never be able to ship. Four checks over the
traced-context index (shard_map/pjit bodies and their callees):

  * **divergent control flow**: a collective reachable under a
    ``lax.cond``/``lax.switch`` whose predicate is derived from
    per-device data. A predicate is UNIFORM only when it provably
    comes from a collective reduction (``psum``/``all_gather``/...)
    or trace-time-static values (constants, shapes); anything chased
    to plain per-device data is divergent. Unresolvable predicates do
    not fire (precision over recall);
  * **branch parity**: both branches of any ``cond`` containing a
    collective must issue the SAME collective sequence (op + axis
    names, in order) — the static deadlock guarantee even when the
    predicate IS uniform;
  * **value-dependent loops**: a collective inside a
    ``lax.while_loop`` body fires unless the loop's cond_fn itself
    derives from a collective (then every device agrees on the trip
    count). ``fori_loop``/``scan`` have static trip counts and are
    exempt;
  * **stepped-deadline convention** (PR 8's mesh program): the chunk
    loop that hosts the ``io_callback`` clock polls must contain NO
    collectives, and within a function that polls, every collective
    must come AFTER the last poll (the final psum'd verdict) — never
    interleaved between polls, where a transiently-divergent verdict
    could desync the mesh.

Plus **axis binding**: every axis name a collective references must be
bound by an enclosing mesh spec somewhere in the package (``Mesh(...,
axis_names=...)``, ``P(...)``/``PartitionSpec`` entries, ``axis_name=``
keywords) — a typo'd axis name fails at trace time on the mesh leg
only, which tier-1's single-host run never exercises.
"""

from __future__ import annotations

import ast

from ..core import (Finding, FuncInfo, Package, call_name, calls_in)

RULE = "collective-safety"

_COLLECTIVES = {"psum", "pmin", "pmax", "pmean", "all_gather",
                "all_to_all", "ppermute", "pshuffle", "psum_scatter",
                "pgather", "pbroadcast"}
# axis-indexed ops: not communication, but their axis names must bind
_AXIS_OPS = _COLLECTIVES | {"axis_index", "axis_size"}
_POLLS = {"io_callback", "pure_callback", "debug_callback"}


# ---------------------------------------------------------------------------
# axis-name harvest
# ---------------------------------------------------------------------------

def _strings(node: ast.AST) -> list[str]:
    return [n.value for n in ast.walk(node)
            if isinstance(n, ast.Constant) and isinstance(n.value, str)]


def _mesh_axes(pkg: Package) -> set[str]:
    """Every axis name bound by a mesh spec anywhere in the package."""
    axes: set[str] = set()
    for m in pkg.modules:
        for node in ast.walk(m.tree):
            if not isinstance(node, ast.Call):
                continue
            base = call_name(node).split(".")[-1]
            if base in ("P", "PartitionSpec"):
                for a in node.args:
                    axes.update(_strings(a))
            elif base in ("Mesh", "make_mesh", "AbstractMesh") and \
                    len(node.args) >= 2:
                axes.update(_strings(node.args[1]))
            for kw in node.keywords:
                if kw.arg in ("axis_name", "axis_names"):
                    axes.update(_strings(kw.value))
    return axes


def _collective_axes(call: ast.Call) -> list[str]:
    """Axis names a collective call references ([] when dynamic)."""
    expr = None
    if len(call.args) >= 2:
        expr = call.args[1]
    elif len(call.args) == 1 and call_name(call).split(".")[-1] in (
            "axis_index", "axis_size"):
        expr = call.args[0]
    for kw in call.keywords:
        if kw.arg in ("axis_name", "axis"):
            expr = kw.value
    return _strings(expr) if expr is not None else []


# ---------------------------------------------------------------------------
# transitive body inspection
# ---------------------------------------------------------------------------

def _body_funcs(pkg: Package, fi: FuncInfo, arg: ast.AST,
                depth: int = 2) -> list[ast.AST]:
    """The AST bodies a control-flow branch argument expands to: the
    lambda/function itself plus resolvable callees, depth-limited."""
    out: list[ast.AST] = []
    seen: set[int] = set()

    def expand(node: ast.AST, fi_ctx: FuncInfo, d: int) -> None:
        if id(node) in seen:
            return
        seen.add(id(node))
        out.append(node)
        if d <= 0:
            return
        body = node.body if isinstance(node, ast.Lambda) else node
        for call in [n for n in ast.walk(body)
                     if isinstance(n, ast.Call)]:
            name = call_name(call)
            if not name:
                continue
            t = pkg.resolve(fi_ctx.module, name, fi_ctx)
            if t is not None:
                expand(t.node, t, d - 1)

    if isinstance(arg, ast.Lambda):
        expand(arg, fi, depth)
    else:
        t = pkg._arg_func(fi.module, fi, arg)
        if t is not None:
            expand(t.node, t, depth)
    return out


def _walk_own(node: ast.AST):
    """Child nodes, not descending into nested defs/lambdas (their
    traced-ness is tracked separately)."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            continue
        yield n
        stack.extend(ast.iter_child_nodes(n))


def _collective_seq(bodies: list[ast.AST]) -> list[tuple[str, tuple]]:
    """Ordered (op, axes) sequence across the expanded bodies."""
    hits: list[tuple[int, int, str, tuple]] = []
    for body in bodies:
        inner = body.body if isinstance(body, ast.Lambda) else body
        nodes = ast.walk(inner) if isinstance(inner, ast.AST) else []
        for n in nodes:
            if isinstance(n, ast.Call):
                base = call_name(n).split(".")[-1]
                if base in _COLLECTIVES:
                    hits.append((n.lineno, n.col_offset, base,
                                 tuple(_collective_axes(n))))
    hits.sort()
    return [(b, a) for _l, _c, b, a in hits]


def _has_poll(bodies: list[ast.AST]) -> bool:
    for body in bodies:
        inner = body.body if isinstance(body, ast.Lambda) else body
        for n in (ast.walk(inner) if isinstance(inner, ast.AST)
                  else []):
            if isinstance(n, ast.Call) and \
                    call_name(n).split(".")[-1] in _POLLS:
                return True
    return False


# ---------------------------------------------------------------------------
# predicate uniformity
# ---------------------------------------------------------------------------

def _uniform(pkg: Package, fi: FuncInfo, expr: ast.AST,
             depth: int = 2) -> bool | None:
    """True = provably mesh-uniform; False = provably per-device;
    None = unknown (never fires)."""
    if isinstance(expr, ast.Constant):
        return True
    # any collective reduction anywhere in the expression makes the
    # whole comparison uniform (all devices compute the same number);
    # axis_index is the opposite — per-device by definition
    for n in ast.walk(expr):
        if isinstance(n, ast.Call):
            base = call_name(n).split(".")[-1]
            if base in _COLLECTIVES:
                return True
            if base == "axis_index":
                return False
    if isinstance(expr, ast.Attribute):
        if expr.attr in ("shape", "ndim", "dtype", "size"):
            return True                # trace-time static
        return None
    if isinstance(expr, (ast.Compare, ast.BoolOp, ast.BinOp,
                         ast.UnaryOp, ast.Subscript, ast.IfExp,
                         ast.Tuple)):
        subs = [c for c in ast.iter_child_nodes(expr)
                if isinstance(c, ast.expr) and not isinstance(
                    c, (ast.cmpop, ast.operator, ast.boolop))]
        verdicts = [_uniform(pkg, fi, c, depth) for c in subs]
        if False in verdicts:
            return False
        if verdicts and all(v is True for v in verdicts):
            return True
        return None
    if isinstance(expr, ast.Call):
        # jnp.any(x) / x.sum(): uniform iff every data operand is —
        # a method call's receiver is an operand too
        operands = list(expr.args)
        if isinstance(expr.func, ast.Attribute):
            operands.append(expr.func.value)
        if not operands:
            return None
        verdicts = [_uniform(pkg, fi, a, depth) for a in operands]
        if False in verdicts:
            return False
        if all(v is True for v in verdicts):
            return True
        return None
    if isinstance(expr, ast.Name):
        if depth <= 0:
            return None
        assigns = []
        for n in ast.walk(fi.node):
            if isinstance(n, ast.Assign):
                for t in n.targets:
                    for tn in ast.walk(t):
                        if isinstance(tn, ast.Name) and \
                                tn.id == expr.id:
                            assigns.append(n.value)
        if assigns:
            verdicts = [_uniform(pkg, fi, a, depth - 1)
                        for a in assigns]
            if False in verdicts:
                return False
            if all(v is True for v in verdicts):
                return True
            return None
        if expr.id in fi.params():
            return False               # raw per-device program input
        return None
    return None


# ---------------------------------------------------------------------------
# the pass
# ---------------------------------------------------------------------------

def check(pkg: Package) -> list[Finding]:
    findings: list[Finding] = []
    axes = _mesh_axes(pkg)
    traced = pkg.traced()
    for fi, why in traced.values():
        m = fi.module
        own_calls = [n for n in _walk_own(fi.node)
                     if isinstance(n, ast.Call)]
        # 1) axis binding
        for call in own_calls:
            base = call_name(call).split(".")[-1]
            if base in _AXIS_OPS:
                for ax in _collective_axes(call):
                    if ax not in axes:
                        findings.append(Finding(
                            RULE, m.relpath, call.lineno,
                            call.col_offset,
                            f"collective `{base}` references axis "
                            f"`{ax}` which no mesh spec in the "
                            f"package binds (traced: {why}) — a "
                            f"typo'd axis fails only on the mesh "
                            f"leg"))
        # 2) cond/switch: divergence + branch parity
        for call in own_calls:
            base = call_name(call).split(".")[-1]
            if base in ("cond", "switch") and len(call.args) >= 2:
                branches = call.args[1:3] if base == "cond" \
                    else call.args[1:]
                seqs = [_collective_seq(_body_funcs(pkg, fi, b))
                        for b in branches]
                if not any(seqs):
                    continue
                if len(seqs) >= 2 and any(s != seqs[0]
                                          for s in seqs[1:]):
                    findings.append(Finding(
                        RULE, m.relpath, call.lineno, call.col_offset,
                        f"`{base}` branches issue MISMATCHED "
                        f"collective sequences {seqs} (traced: {why})"
                        f" — devices taking different branches "
                        f"deadlock in the unmatched reduce"))
                if _uniform(pkg, fi, call.args[0]) is False:
                    findings.append(Finding(
                        RULE, m.relpath, call.lineno, call.col_offset,
                        f"collective under `{base}` with a per-device "
                        f"predicate (traced: {why}) — derive the "
                        f"predicate from a collective reduction "
                        f"(psum/all_gather) so every device takes "
                        f"the same branch"))
            elif base == "while_loop" and len(call.args) >= 2:
                body_seq = _collective_seq(
                    _body_funcs(pkg, fi, call.args[1]))
                if not body_seq:
                    continue
                cond_seq = _collective_seq(
                    _body_funcs(pkg, fi, call.args[0]))
                if not cond_seq:
                    findings.append(Finding(
                        RULE, m.relpath, call.lineno, call.col_offset,
                        f"collective inside a value-dependent "
                        f"while_loop body whose cond is not itself "
                        f"collective-derived (traced: {why}) — "
                        f"devices can disagree on the trip count and "
                        f"deadlock"))
        # 3) stepped-deadline convention
        poll_lines = [c.lineno for c in own_calls
                      if call_name(c).split(".")[-1] in _POLLS]
        if poll_lines:
            last_poll = max(poll_lines)
            for call in own_calls:
                base = call_name(call).split(".")[-1]
                if base in _COLLECTIVES and call.lineno <= last_poll:
                    findings.append(Finding(
                        RULE, m.relpath, call.lineno, call.col_offset,
                        f"collective `{base}` interleaved with "
                        f"stepped deadline polls (traced: {why}) — "
                        f"the poll phase must finish before the "
                        f"final collective verdict (PR 8 stepped-"
                        f"deadline convention)"))
        for call in own_calls:
            base = call_name(call).split(".")[-1]
            idx = {"fori_loop": 2, "scan": 0}.get(base)
            if idx is None or idx >= len(call.args):
                continue
            bodies = _body_funcs(pkg, fi, call.args[idx])
            if _has_poll(bodies):
                for op, ax in _collective_seq(bodies):
                    findings.append(Finding(
                        RULE, m.relpath, call.lineno, call.col_offset,
                        f"collective `{op}` inside the stepped poll "
                        f"loop (traced: {why}) — the chunk loop "
                        f"hosting the io_callback deadline polls "
                        f"must issue NO collectives"))
    return findings
