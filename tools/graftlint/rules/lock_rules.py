"""Rule family 5 — lock discipline + acquisition-order graph.

Two failure shapes the dispatch scheduler / autotuner / resident cache
triangle can produce:

  * a BLOCKING call (device dispatch + collect, sleeps, file/network
    IO, thread joins) while holding one of those locks — every other
    search on the node convoys behind a device round trip;
  * an acquisition-order CYCLE between locks — the classic deadlock,
    invisible until two requests interleave just so.

Lock discovery is structural: `X = threading.Lock()` at module level
and `self.X = threading.Lock()` in any method. A suppression on the
DEFINITION line (`# graftlint: ok(lock-discipline): <why>`) declares a
serialization latch — a lock whose entire purpose is to be held across
the blocking section (the dispatch scheduler's leader lock) — and
exempts it from the blocking-call rule while keeping it in the order
graph.

Held regions: `with X:` bodies, plus the `if X.acquire(...):` body
(the scheduler's try-acquire leader idiom). Blocking calls are matched
lexically in the region and one call level deep through same-module
functions. The order graph adds an edge L1 -> L2 whenever L2 is
acquired anywhere inside L1's held region (again one call level deep);
a cycle in that graph is a `lock-order` finding listing the loop.
"""

from __future__ import annotations

import ast

from ..core import Finding, LockInfo, Package, call_name, calls_in, dotted

RULE = "lock-discipline"
RULE_ORDER = "lock-order"

_BLOCKING_TAILS = {
    "sleep": "time.sleep",
    "finish": "pending-dispatch collect `.finish()`",
    "block_until_ready": "device sync `block_until_ready`",
    "device_get": "device collect `jax.device_get`",
    "join": "thread join",
    "wait": "event/condition wait",
    "result": "future result wait",
    "msearch": "device dispatch `.msearch(...)`",
    "execute_segment": "synchronous device dispatch",
    "urlopen": "network IO",
    "compile": "XLA compilation",
}
# file IO counts as blocking only as the builtin (method .open() on an
# object is usually a cheap handle)
_BLOCKING_EXACT = {"open": "file IO `open(...)`"}

# The blocking-call check is scoped to the HOT-PATH lock owners the
# issue names (dispatch scheduler, autotuner/executor, resident cache):
# a control-plane lock persisting settings under itself is a deliberate
# atomicity choice, not a convoy risk. The acquisition-ORDER graph
# stays package-wide. Snippet modules (test fixtures) always count hot.
# `tiering` joined with the tile pager (PR 11): its LRU lock sits on
# every tiered dispatch's fetch path — uploads/holds must stay outside.
# `ann` joined with the IVF subsystem (PR 14): its ensure lock sits on
# every vector search's probe path — the k-means build and device
# uploads run OUTSIDE it (check-build-install), and the lint keeps it
# that way.
# `store`/`translog` joined with the durability path (ISSUE 15): fault
# hooks and fsyncs sit at every write boundary — any lock these
# modules ever grow must not hold across them.
# `devbuild` joined with the device-parallel builder (ISSUE 16): its
# config/stats locks sit inside every refresh and compaction — the
# device programs themselves (sort, scatter, k-means) must dispatch
# OUTSIDE them; lock bodies stay pure counter/flag mutations.
# `membership` joined with elastic pod membership (ISSUE 19): the
# ledger/lease locks sit on every exec fence and every quorum round —
# PodCoordinator deliberately gathers votes OUTSIDE them, and the lint
# keeps any future round logic from creeping inside a lock body.
_HOT_LOCK_MODULES = {"dispatch", "resident", "executor", "shard_searcher",
                     "distributed", "breaker", "repack", "traffic",
                     "tiering", "multihost", "clocksync", "ann",
                     "store", "translog", "devbuild", "membership"}


def _hot(li: LockInfo) -> bool:
    base = li.module.relpath.rsplit("/", 1)[-1].removesuffix(".py")
    return li.module.snippet or base in _HOT_LOCK_MODULES


def _lock_for(m, fi, expr: ast.AST, pkg: Package) -> LockInfo | None:
    """Resolve a `with X:` / `X.acquire()` receiver to a LockInfo."""
    name = dotted(expr)
    if not name:
        return None
    if name.startswith("self."):
        suffix = name.split(".", 1)[1]
    else:
        suffix = name
    li = m.locks.get(suffix)
    if li is not None:
        return li
    # cross-module: unique suffix match package-wide (the scheduler's
    # lock used through `node._dispatch._mx` etc.)
    hits = [mm.locks[suffix] for mm in pkg.modules if suffix in mm.locks]
    return hits[0] if len(hits) == 1 else None


def _held_regions(m, fi, pkg) -> list[tuple[LockInfo, list[ast.stmt], int]]:
    """(lock, body statements, acquire lineno) for every held region."""
    out = []
    for node in ast.walk(fi.node):
        if isinstance(node, ast.With):
            for item in node.items:
                li = _lock_for(m, fi, item.context_expr, pkg)
                if li is not None:
                    out.append((li, node.body, node.lineno))
        elif isinstance(node, ast.If):
            # `if X.acquire(blocking=False):` — the scheduler's
            # try-acquire leader idiom; the test may BE the call, so
            # walk the test inclusively
            for call in [n for n in ast.walk(node.test)
                         if isinstance(n, ast.Call)]:
                if call_name(call).split(".")[-1] == "acquire":
                    li = _lock_for(m, fi, call.func.value, pkg) \
                        if isinstance(call.func, ast.Attribute) else None
                    if li is not None:
                        out.append((li, node.body, node.lineno))
    return out


def _blocking_in(stmts: list[ast.stmt], m, fi, pkg: Package,
                 depth: int, held: LockInfo | None = None
                 ) -> list[tuple[ast.Call, str, str]]:
    """(call, what, via) blocking calls lexically in stmts, expanding
    through same-module callees `depth` levels deep."""
    out = []
    for s in stmts:
        for call in [n for n in ast.walk(s) if isinstance(n, ast.Call)]:
            name = call_name(call)
            tail = name.split(".")[-1] if name else ""
            what = _BLOCKING_EXACT.get(name) or _BLOCKING_TAILS.get(tail)
            if what and held is not None and tail in ("wait", "acquire") \
                    and isinstance(call.func, ast.Attribute) and \
                    _lock_for(m, fi, call.func.value, pkg) is held:
                # Condition.wait()/re-acquire on the HELD lock itself is
                # the cv pattern (wait releases while parked), not a
                # convoy
                what = None
            if what:
                out.append((call, what, ""))
                continue
            if depth > 0 and name:
                callee = pkg.resolve(m, name, fi)
                if callee is not None and callee.module is m:
                    for c2, w2, _via in _blocking_in(
                            callee.node.body, m, callee, pkg, depth - 1,
                            held):
                        out.append((call, w2,
                                    f" (via {callee.qualname}:{c2.lineno})"))
    # de-dup per (call site, what)
    seen = set()
    uniq = []
    for call, what, via in out:
        k = (call.lineno, call.col_offset, what)
        if k not in seen:
            seen.add(k)
            uniq.append((call, what, via))
    return uniq


def _acquired_in(stmts: list[ast.stmt], m, fi, pkg: Package,
                 depth: int) -> list[tuple[LockInfo, int]]:
    out = []
    for s in stmts:
        for node in ast.walk(s):
            if isinstance(node, ast.With):
                for item in node.items:
                    li = _lock_for(m, fi, item.context_expr, pkg)
                    if li is not None:
                        out.append((li, node.lineno))
            elif isinstance(node, ast.Call) and \
                    call_name(node).split(".")[-1] == "acquire" and \
                    isinstance(node.func, ast.Attribute):
                li = _lock_for(m, fi, node.func.value, pkg)
                if li is not None:
                    out.append((li, node.lineno))
        if depth > 0:
            for call in calls_in(s):
                name = call_name(call)
                callee = pkg.resolve(m, name, fi) if name else None
                if callee is not None and callee.module is m:
                    for li, _ln in _acquired_in(callee.node.body, m,
                                                callee, pkg, depth - 1):
                        out.append((li, call.lineno))
    return out


def check(pkg: Package) -> list[Finding]:
    findings: list[Finding] = []
    edges: dict[tuple[str, str], tuple[str, int]] = {}
    for m in pkg.modules:
        for fi in m.functions:
            for li, body, _ln in _held_regions(m, fi, pkg):
                if not li.exempt and _hot(li):
                    for call, what, via in _blocking_in(
                            body, m, fi, pkg, depth=2, held=li):
                        findings.append(Finding(
                            RULE, m.relpath, call.lineno,
                            call.col_offset,
                            f"blocking call — {what}{via} — while "
                            f"holding `{li.key}` in {fi.qualname}"))
                for li2, ln2 in _acquired_in(body, m, fi, pkg, depth=1):
                    if li2.key != li.key:
                        edges.setdefault((li.key, li2.key),
                                         (m.relpath, ln2))
    findings.extend(_cycles(edges))
    return findings


def _cycles(edges: dict[tuple[str, str], tuple[str, int]]) -> list[Finding]:
    graph: dict[str, set[str]] = {}
    for a, b in edges:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
    out = []
    color: dict[str, int] = {}
    stack: list[str] = []
    reported: set[frozenset] = set()

    def dfs(v: str):
        color[v] = 1
        stack.append(v)
        for w in sorted(graph.get(v, ())):
            if color.get(w, 0) == 0:
                dfs(w)
            elif color.get(w) == 1:
                cyc = stack[stack.index(w):] + [w]
                key = frozenset(cyc)
                if key not in reported:
                    reported.add(key)
                    path, line = edges.get((v, w)) or \
                        edges.get((w, cyc[1])) or ("<graph>", 0)
                    out.append(Finding(
                        RULE_ORDER, path, line, 0,
                        "lock acquisition-order cycle: "
                        + " -> ".join(cyc)
                        + " — pick ONE order and stick to it"))
        stack.pop()
        color[v] = 2

    for v in sorted(graph):
        if color.get(v, 0) == 0:
            dfs(v)
    return out
