"""Rule family 2 — trace purity.

A host sync (`.item()`, `block_until_ready`, `device_get`) or Python
side effect (wall-clock reads, prints, env reads, IO, mutation of
module/closure state) inside a traced body either crashes at trace
time, silently bakes one request's value into every later execution of
the compiled program, or forces a device->host round trip in the middle
of the device program — the tail-latency cliffs the paper's read path
exists to avoid. The ONLY sanctioned device->host bridge is
`io_callback` (the `_step_poll` deadline poll in ops/scoring's stepped
tile loop is the exemplar), which core.py's traced-context computation
already exempts as a host half.

Traced contexts come from `Package.traced()`: jit-decorated functions,
bodies handed to lax control flow / pallas_call / shard_map, and their
package-resolvable callees, to a fixpoint.
"""

from __future__ import annotations

import ast

from ..core import Finding, Package, call_name, calls_in

RULE = "trace-purity"

# unambiguous host syncs / side effects: flagged anywhere inside a
# traced body. (Plain float()/int() on statics is legitimate trace-time
# Python, so casts are NOT in this list — `.item()` is the sync spelling
# this codebase would use on a traced value.)
_FORBIDDEN_TAILS = {
    "item": "host sync `.item()`",
    "block_until_ready": "host sync `block_until_ready`",
    "device_get": "host transfer `jax.device_get`",
    "copy_to_host_async": "host transfer `copy_to_host_async`",
    "tolist": "host sync `.tolist()`",
    "print": "side effect `print(...)`",
    "sleep": "side effect `time.sleep`",
}
_FORBIDDEN_DOTTED = {
    "time.time": "wall-clock read `time.time()`",
    "time.monotonic": "wall-clock read `time.monotonic()`",
    "time.perf_counter": "wall-clock read `time.perf_counter()`",
    "_time.perf_counter": "wall-clock read `perf_counter()`",
    "np.asarray": "host materialization `np.asarray(...)`",
    "np.array": "host materialization `np.array(...)`",
    "numpy.asarray": "host materialization `np.asarray(...)`",
    "np.ascontiguousarray": "host materialization",
    "os.environ.get": "env read `os.environ`",
    "os.getenv": "env read `os.getenv`",
    "open": "file IO `open(...)`",
}
# mutating method calls on names from an enclosing scope
_MUTATORS = {"append", "update", "setdefault", "extend", "add", "pop",
             "clear", "remove"}


def _local_stores(func: ast.FunctionDef) -> set[str]:
    names = {a.arg for a in func.args.args + func.args.kwonlyargs
             + func.args.posonlyargs}
    if func.args.vararg:
        names.add(func.args.vararg.arg)
    if func.args.kwarg:
        names.add(func.args.kwarg.arg)
    for n in ast.walk(func):
        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store):
            names.add(n.id)
        elif isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and n is not func:
            names.add(n.name)
        elif isinstance(n, ast.comprehension):
            for t in ast.walk(n.target):
                if isinstance(t, ast.Name):
                    names.add(t.id)
    return names


def _walk_own(func: ast.FunctionDef):
    stack = list(ast.iter_child_nodes(func))
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield n
        stack.extend(ast.iter_child_nodes(n))


def check(pkg: Package) -> list[Finding]:
    findings: list[Finding] = []
    traced = pkg.traced()
    for fi, why in traced.values():
        m = fi.module
        locals_ = _local_stores(fi.node)
        # closure variables of an enclosing function are TRACE-LOCAL
        # (fresh per trace) — mutating a parent's memo dict or a pallas
        # out_ref closure is not persisted host state; only module-level
        # names are
        p = fi.parent
        while p is not None:
            locals_ |= _local_stores(p.node)
            p = p.parent
        for call in calls_in(fi.node, skip_nested=True):
            name = call_name(call)
            tail = name.split(".")[-1] if name else ""
            msg = _FORBIDDEN_DOTTED.get(name) or (
                _FORBIDDEN_TAILS.get(tail)
                if tail in _FORBIDDEN_TAILS else None)
            if tail == "print" and name != "print":
                msg = None          # obj.print() is not the builtin
            if msg:
                findings.append(Finding(
                    RULE, m.relpath, call.lineno, call.col_offset,
                    f"{msg} inside traced code ({why}) — route through "
                    f"io_callback or move to bind time"))
                continue
            # closure/global mutation via method call
            if tail in _MUTATORS and isinstance(call.func, ast.Attribute) \
                    and isinstance(call.func.value, ast.Name) \
                    and call.func.value.id not in locals_:
                findings.append(Finding(
                    RULE, m.relpath, call.lineno, call.col_offset,
                    f"mutation `{call.func.value.id}.{tail}(...)` of "
                    f"enclosing-scope state inside traced code ({why}) — "
                    f"trace-time mutation escapes the trace cache"))
        # closure/global mutation via subscript store: CACHE[k] = v
        # (nested defs are traced — and checked — in their own right)
        for n in _walk_own(fi.node):
            if isinstance(n, (ast.Assign, ast.AugAssign)):
                targets = n.targets if isinstance(n, ast.Assign) \
                    else [n.target]
                for t in targets:
                    if isinstance(t, ast.Subscript) and \
                            isinstance(t.value, ast.Name) and \
                            t.value.id not in locals_:
                        findings.append(Finding(
                            RULE, m.relpath, n.lineno, n.col_offset,
                            f"subscript store into enclosing-scope "
                            f"`{t.value.id}[...]` inside traced code "
                            f"({why})"))
    return findings
