"""Rule family 3 — donation safety.

`donate_argnums` hands a buffer's memory to XLA: the moment the donated
call is issued, the Python-side array is invalid and any later read
returns garbage (or raises, backend-depending). resident.py's staged
wire buffers are the live instance — the wire is donated to the pinned
stepped executable, so everything after the invocation must work from
the HOST copy (`wire`), never `wire_dev`.

Donating callables are discovered three ways:

  * a jit declaration with `donate_argnums` (decorator or assignment
    form), invoked by name;
  * a variable assigned from `<donating>.lower(...).compile()` — the
    AOT form — and invoked through that variable;
  * the resident-entry convention: an attribute call `X.compiled(...)`
    in a module that defines at least one donating jitted function —
    the pinned-executable invocation, whose donation facts come from
    that jit declaration.

`.lower(...)` itself only traces (nothing is donated), so it is never a
donating invocation.

A read is any Load of the donated name on a line after the donating
call with no intervening rebind (lineno ordering approximates paths —
good enough for straight-line dispatch code, and wrong only toward
false negatives on exotic control flow).
"""

from __future__ import annotations

import ast

from ..core import Finding, Package, calls_in, call_name, dotted

RULE = "donation-safety"


def _donating_jits(m) -> dict[str, tuple[int, ...]]:
    return {name: info.donate_argnums for name, info in m.jit.items()
            if info.donate_argnums}


def check(pkg: Package) -> list[Finding]:
    findings: list[Finding] = []
    for m in pkg.modules:
        module_donors = _donating_jits(m)
        # cross-module: imported donating jits
        for other in pkg.modules:
            if other is m:
                continue
            for name, argnums in _donating_jits(other).items():
                if name in m.imports:
                    module_donors.setdefault(name, argnums)
        # the resident-entry convention needs SOME donating jit to take
        # its donation facts from; ambiguity (several with different
        # argnums) keeps the convention off in that module
        compiled_argnums = None
        local = list(_donating_jits(m).values())
        if local and all(a == local[0] for a in local):
            compiled_argnums = local[0]
        for fi in m.functions:
            aot_vars: dict[str, tuple[int, ...]] = {}
            for node in ast.walk(fi.node):
                if isinstance(node, ast.Assign) and \
                        isinstance(node.value, ast.Call):
                    src = dotted(node.value.func)
                    # X = f.lower(...).compile()  (dotted -> "f.lower().compile")
                    for name, argnums in module_donors.items():
                        if src.startswith(f"{name}.lower") and \
                                src.endswith("compile"):
                            for t in node.targets:
                                if isinstance(t, ast.Name):
                                    aot_vars[t.id] = argnums
            for call in calls_in(fi.node):
                name = call_name(call)
                if not name or name.split(".")[-1] in ("lower", "compile"):
                    continue
                argnums = None
                if name in module_donors:
                    argnums = module_donors[name]
                elif name in aot_vars:
                    argnums = aot_vars[name]
                elif name.endswith(".compiled") and \
                        compiled_argnums is not None:
                    argnums = compiled_argnums
                if not argnums:
                    continue
                for i in argnums:
                    if i >= len(call.args):
                        continue
                    arg = call.args[i]
                    if not isinstance(arg, ast.Name):
                        continue
                    findings.extend(_reads_after(
                        m, fi, call, arg.id, name))
    return findings


def _reads_after(m, fi, call: ast.Call, var: str,
                 callee: str) -> list[Finding]:
    out = []
    rebind_line = None
    for n in ast.walk(fi.node):
        # a Store on the donating call's own line is the assignment
        # receiving its result (`buf = step(buf, x)`) — that rebind
        # makes later reads legal
        if isinstance(n, ast.Name) and n.id == var and \
                isinstance(n.ctx, ast.Store) and n.lineno >= call.lineno:
            rebind_line = n.lineno if rebind_line is None \
                else min(rebind_line, n.lineno)
    for n in ast.walk(fi.node):
        if not (isinstance(n, ast.Name) and n.id == var
                and isinstance(n.ctx, ast.Load)
                and n.lineno > call.end_lineno):
            continue
        if rebind_line is not None and n.lineno > rebind_line:
            continue
        out.append(Finding(
            RULE, m.relpath, n.lineno, n.col_offset,
            f"`{var}` read after being DONATED to `{callee}(...)` at "
            f"line {call.lineno} in {fi.qualname} — the buffer's memory "
            f"belongs to XLA now; keep a host copy instead"))
    return out
