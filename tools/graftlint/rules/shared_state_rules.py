"""Rule family 6 — shared-state races (Eraser-style lockset pass).

Lock DISCIPLINE (family 5) checks what you do while holding a lock;
this family checks lock SUFFICIENCY: every piece of state reachable
from more than one thread must have a non-empty COMMON lockset across
all of its access sites — the classic Eraser algorithm, run statically
over the hot-path modules the dispatch/traffic/resident/repack/tiering
stack made deeply concurrent.

What counts as shared:

  * instance attributes of a SHARED CLASS — a class that owns a lock
    attribute (it declared itself concurrent), has a method discovered
    as a thread entry (``threading.Thread(target=...)``, pool
    ``submit``/``execute``, ``weakref.finalize`` callbacks, io_callback
    host halves), or whose instances are published at module level
    (``pager = TilePager()``) or into an attribute of another shared
    class (``self._m1 = EWMA()``), to a fixpoint;
  * module-level globals of a hot module that are REBOUND or mutated
    (subscript store / mutator method on a plain container) from
    function scope — the module list itself declares these modules
    concurrent, so every such write needs a lock.

Locksets are computed lexically (``with lock:`` regions, the
``if lock.acquire(...):`` try-acquire idiom) plus the codebase's
``*_locked`` naming convention: a method whose name ends in ``_locked``
inherits the intersection of the locks held at its same-class call
sites (to a small fixpoint, so ``_trim_locked`` -> ``_evict_locked``
chains resolve).

Exemptions, in the order they are applied:

  * attributes/globals whose every write happens in ``__init__`` /
    module scope (init-confinement: publication is the only hand-off);
  * attributes initialized to an internally-synchronized object — a
    stdlib threading/queue primitive or a PACKAGE class that owns a
    lock attribute (``CounterMetric``, ``TilePager``, ...): method
    calls on such an attribute serialize themselves (rebinding the
    attribute still counts);
  * a DECLARED GIL-atomic attribute: ``# graftlint: ok(
    shared-state-race): why`` on the attribute's ``__init__``
    assignment line (or the comment block above it) exempts the
    attribute package-wide — the declaration is the audit trail that a
    single-op counter read/write is intentionally unlocked. Declared,
    never assumed;
  * ordinary same-line suppressions via the existing machinery.

One finding per racy attribute/global (at its worst access site), so
the initial package run is triageable fix-by-fix.
"""

from __future__ import annotations

import ast
import os

from ..core import (Finding, FuncInfo, Module, Package, call_name,
                    dotted)

RULE = "shared-state-race"

# the hot-path modules the issue names: the concurrency surface built
# by PRs 3-11. Snippet modules (test fixtures) always count hot.
# `devbuild` joined with the device-parallel builder (ISSUE 16): every
# refresh/compaction thread mutates its config + counters.
# `membership` joined with elastic pod membership (ISSUE 19): ledger,
# lease, and abandoned-seq state are hit from exec handlers, heartbeat
# threads, and driver retries at once.
_HOT_MODULES = {"dispatch", "traffic", "resident", "repack", "tiering",
                "executor", "cache", "faults", "metrics", "devbuild",
                "membership"}

# stdlib constructor tails whose instances serialize themselves (or are
# thread-confined by construction, like threading.local); package
# classes that OWN a lock attribute are computed, not listed
_SYNC_TAILS = {"Lock", "RLock", "Condition", "Event", "Semaphore",
               "BoundedSemaphore", "Barrier", "local", "Queue",
               "SimpleQueue", "LifoQueue", "PriorityQueue", "ref",
               "WeakValueDictionary", "WeakKeyDictionary",
               "WeakSet"}
_CONTAINER_TAILS = {"dict", "list", "set", "OrderedDict", "defaultdict",
                    "deque", "Counter"}
# method calls that mutate a plain container receiver
_MUTATORS = {"append", "extend", "insert", "add", "update", "setdefault",
             "pop", "popitem", "remove", "discard", "clear",
             "appendleft", "extendleft", "move_to_end", "sort",
             "reverse"}


def _hot(m: Module) -> bool:
    base = m.relpath.rsplit("/", 1)[-1].removesuffix(".py")
    return m.snippet or base in _HOT_MODULES


def _mod_tag(m: Module) -> str:
    return os.path.splitext(os.path.basename(m.relpath))[0]


# ---------------------------------------------------------------------------
# init-value classification
# ---------------------------------------------------------------------------

def _init_kind(value: ast.AST, sync_classes: set[str]) -> str:
    """'sync' | 'container' | 'other' for an __init__/module-level
    assignment's right-hand side."""
    if isinstance(value, (ast.Dict, ast.List, ast.Set, ast.ListComp,
                          ast.DictComp, ast.SetComp)):
        return "container"
    if isinstance(value, ast.Call):
        tail = call_name(value).split(".")[-1]
        if tail in _SYNC_TAILS or tail in sync_classes:
            return "sync"
        if tail in _CONTAINER_TAILS:
            return "container"
    return "other"


def _class_locks(m: Module) -> dict[str, set[str]]:
    """class name -> its OWN lock attribute names. Computed directly
    (not from Module.locks, whose suffix keying collides when several
    classes in one module all name their lock `_lock`)."""
    out: dict[str, set[str]] = {}
    for fi in m.functions:
        if not fi.class_name:
            continue
        for n in ast.walk(fi.node):
            if isinstance(n, ast.Assign) and isinstance(n.value,
                                                        ast.Call):
                base = call_name(n.value).split(".")[-1]
                if base not in ("Lock", "RLock", "Condition"):
                    continue
                for t in n.targets:
                    if isinstance(t, ast.Attribute) and \
                            isinstance(t.value, ast.Name) and \
                            t.value.id == "self":
                        out.setdefault(fi.class_name,
                                       set()).add(t.attr)
    return out


def _lock_owning_classes(pkg: Package) -> set[str]:
    """Bare names of package classes that own a lock attribute — their
    instances are treated as internally synchronized receivers."""
    owners: set[str] = set()
    for m in pkg.modules:
        owners.update(_class_locks(m))
    return owners


# ---------------------------------------------------------------------------
# access-site collection with a held-lock stack
# ---------------------------------------------------------------------------

class _Site:
    __slots__ = ("key", "kind", "line", "col", "func", "locks",
                 "in_init")

    def __init__(self, key, kind, node, func, locks, in_init):
        self.key = key
        self.kind = kind          # "write" | "mutate" | "read"
        self.line = node.lineno
        self.col = getattr(node, "col_offset", 0)
        self.func = func
        self.locks = frozenset(locks)
        self.in_init = in_init


def _lock_key(m: Module, expr: ast.AST, pkg: Package) -> str | None:
    name = dotted(expr)
    if not name:
        return None
    suffix = name.split(".", 1)[1] if name.startswith("self.") else name
    li = m.locks.get(suffix)
    if li is not None:
        return li.key
    hits = [mm.locks[suffix] for mm in pkg.modules
            if suffix in mm.locks]
    return hits[0].key if len(hits) == 1 else None


def _collect_func(m: Module, fi: FuncInfo, pkg: Package,
                  inherited: frozenset,
                  self_calls: "list[tuple[str, frozenset]]",
                  sites: list[_Site],
                  attr_mode: bool, globals_: set[str],
                  own_locks: set[str] = frozenset()) -> None:
    """Walk one function, tracking held locks, emitting access sites.

    attr_mode: collect `self.X` accesses (class pass); otherwise
    collect module-global writes (global pass). `self_calls` receives
    (bare method name, held set) for every `self.meth()` call so the
    `_locked` inheritance fixpoint can run. `own_locks` are the
    enclosing class's OWN lock attribute names — `with self.X:` keys
    per class, immune to same-suffix collisions across classes."""
    in_init = fi.name == "__init__"
    mod = _mod_tag(m)

    def lock_of(expr):
        name = dotted(expr)
        if name.startswith("self.") and \
                name.split(".", 1)[1] in own_locks:
            return f"{mod}.{fi.class_name}.{name.split('.', 1)[1]}"
        return _lock_key(m, expr, pkg)

    def emit(key, kind, node, held):
        sites.append(_Site(key, kind, node, fi, held, in_init))

    def scan_expr(node: ast.AST, held: frozenset) -> None:
        """Accesses inside one expression/simple statement."""
        consumed: set[int] = set()
        for n in ast.walk(node):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                continue
            if isinstance(n, ast.Call):
                # local calls feed the `_locked` inheritance pass:
                # `self.meth()` for the class pass, bare-name calls
                # for module-level helpers
                cn = call_name(n)
                if cn.startswith("self.") and cn.count(".") == 1:
                    self_calls.append((cn.split(".")[1], held))
                elif cn and "." not in cn:
                    self_calls.append((cn, held))
                # mutator call on a tracked receiver
                if isinstance(n.func, ast.Attribute) and \
                        n.func.attr in _MUTATORS:
                    recv = n.func.value
                    if attr_mode and isinstance(recv, ast.Attribute) \
                            and isinstance(recv.value, ast.Name) \
                            and recv.value.id == "self":
                        emit(recv.attr, "mutate", n, held)
                        consumed.add(id(recv))
                    elif not attr_mode and isinstance(recv, ast.Name) \
                            and recv.id in globals_:
                        emit(recv.id, "mutate", n, held)
            elif isinstance(n, ast.Subscript):
                base = n.value
                if isinstance(n.ctx, (ast.Store, ast.Del)):
                    if attr_mode and isinstance(base, ast.Attribute) \
                            and isinstance(base.value, ast.Name) \
                            and base.value.id == "self":
                        emit(base.attr, "mutate", n, held)
                        consumed.add(id(base))
                    elif not attr_mode and isinstance(base, ast.Name) \
                            and base.id in globals_:
                        emit(base.id, "mutate", n, held)
        if not attr_mode:
            return
        for n in ast.walk(node):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                continue
            if isinstance(n, ast.Attribute) and \
                    isinstance(n.value, ast.Name) and \
                    n.value.id == "self" and id(n) not in consumed:
                if isinstance(n.ctx, (ast.Store, ast.Del)):
                    emit(n.attr, "write", n, held)
                else:
                    emit(n.attr, "read", n, held)

    def scan_global_assigns(s: ast.stmt, held: frozenset) -> None:
        """Rebinding writes to module globals (requires a `global`
        declaration somewhere in the function — a bare Name store
        without one is a local)."""
        targets = []
        if isinstance(s, ast.Assign):
            targets = s.targets
        elif isinstance(s, (ast.AugAssign, ast.AnnAssign)):
            targets = [s.target]
        for t in targets:
            for n in ast.walk(t):
                if isinstance(n, ast.Name) and n.id in globals_ and \
                        n.id in declared_global:
                    emit(n.id, "write", n, held)

    declared_global: set[str] = set()
    for n in ast.walk(fi.node):
        if isinstance(n, ast.Global):
            declared_global.update(n.names)

    def visit(stmts: list[ast.stmt], held: frozenset) -> None:
        for s in stmts:
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue               # processed as their own function
            if isinstance(s, ast.With):
                extra = set()
                for item in s.items:
                    scan_expr(item.context_expr, held)
                    lk = lock_of(item.context_expr)
                    if lk is not None:
                        extra.add(lk)
                visit(s.body, held | frozenset(extra))
                continue
            if isinstance(s, ast.If):
                scan_expr(s.test, held)
                extra = set()
                for call in [n for n in ast.walk(s.test)
                             if isinstance(n, ast.Call)]:
                    if call_name(call).split(".")[-1] == "acquire" and \
                            isinstance(call.func, ast.Attribute):
                        lk = lock_of(call.func.value)
                        if lk is not None:
                            extra.add(lk)
                visit(s.body, held | frozenset(extra))
                visit(s.orelse, held)
                continue
            if isinstance(s, (ast.For, ast.AsyncFor)):
                scan_expr(s.iter, held)
                scan_expr(s.target, held)
                scan_global_assigns(s, held)
                visit(s.body, held)
                visit(s.orelse, held)
                continue
            if isinstance(s, ast.While):
                scan_expr(s.test, held)
                visit(s.body, held)
                visit(s.orelse, held)
                continue
            if isinstance(s, ast.Try):
                visit(s.body, held)
                for h in s.handlers:
                    visit(h.body, held)
                visit(s.orelse, held)
                visit(s.finalbody, held)
                continue
            scan_expr(s, held)
            if not attr_mode:
                scan_global_assigns(s, held)

    visit(fi.node.body, inherited)


# ---------------------------------------------------------------------------
# the pass
# ---------------------------------------------------------------------------

def _shared_classes(m: Module, pkg: Package,
                    lock_owners: set[str]) -> dict[str, str]:
    """class name -> why-shared for one hot module."""
    shared: dict[str, str] = {}
    class_names = {fi.class_name for fi in m.functions if fi.class_name}
    for name in class_names:
        if name in lock_owners:
            shared.setdefault(name, "owns a lock")
    for fi, why in pkg.thread_entries().values():
        if fi.module is m and fi.class_name:
            shared.setdefault(fi.class_name, f"thread entry ({why})")
    # module-level publication: stats = TieringStats()
    for node in m.tree.body:
        if isinstance(node, ast.Assign) and isinstance(node.value,
                                                       ast.Call):
            tail = call_name(node.value).split(".")[-1]
            if tail in class_names:
                shared.setdefault(tail, "published at module level")
    # fixpoint: instances stored into attributes of shared classes
    changed = True
    while changed:
        changed = False
        for fi in m.functions:
            if fi.class_name not in shared:
                continue
            for n in ast.walk(fi.node):
                if isinstance(n, ast.Assign) and \
                        isinstance(n.value, ast.Call):
                    tail = call_name(n.value).split(".")[-1]
                    if tail in class_names and tail not in shared:
                        shared[tail] = \
                            f"published via {fi.qualname}"
                        changed = True
    return shared


def _locked_inheritance(m: Module, pkg: Package,
                        funcs: list[FuncInfo],
                        locked_fns: dict[str, FuncInfo],
                        attr_mode: bool, globals_: set[str],
                        own_of) -> dict[int, frozenset]:
    """`*_locked` convention, shared by the class pass (methods called
    as `self.X_locked()`) and the global pass (module helpers like the
    executor's `_autotune_persist_locked`): each such function inherits
    the INTERSECTION of the locks held at its call sites, iterated to
    a small fixpoint so `_trim_locked` -> `_evict_locked` chains
    resolve. `own_of(fi)` supplies the enclosing class's own lock
    names for per-class `with self.X:` keying."""
    inherited: dict[int, frozenset] = {
        id(fi.node): frozenset() for fi in funcs}
    if not locked_fns:
        return inherited
    for _round in range(3):
        changed = False
        # collect call sites with the CURRENT inheritance estimate
        calls: dict[str, list[frozenset]] = {n: [] for n in locked_fns}
        for fi in funcs:
            recs: list[tuple[str, frozenset]] = []
            _collect_func(m, fi, pkg, inherited[id(fi.node)], recs,
                          [], attr_mode, globals_, own_of(fi))
            for name, held in recs:
                if name in calls:
                    calls[name].append(held)
        for name, fi in locked_fns.items():
            sites = calls[name]
            new = (frozenset.intersection(*sites) if sites
                   else frozenset())
            if new != inherited[id(fi.node)]:
                inherited[id(fi.node)] = new
                changed = True
        if not changed:
            break
    return inherited


def _inherited_locks(methods: list[FuncInfo], m: Module,
                     pkg: Package,
                     own_locks: set[str]) -> dict[int, frozenset]:
    return _locked_inheritance(
        m, pkg, methods,
        {fi.name: fi for fi in methods
         if fi.name.endswith("_locked")},
        True, set(), lambda _fi: own_locks)


def check(pkg: Package) -> list[Finding]:
    findings: list[Finding] = []
    lock_owners = _lock_owning_classes(pkg)
    for m in pkg.modules:
        if not _hot(m):
            continue
        findings.extend(_check_classes(m, pkg, lock_owners))
        findings.extend(_check_globals(m, pkg, lock_owners))
    return findings


def _check_classes(m: Module, pkg: Package,
                   lock_owners: set[str]) -> list[Finding]:
    out: list[Finding] = []
    mod = _mod_tag(m)
    shared = _shared_classes(m, pkg, lock_owners)
    cls_locks = _class_locks(m)
    for cls, why in sorted(shared.items()):
        methods = [fi for fi in m.functions if fi.class_name == cls]
        if not methods:
            continue
        own_locks = cls_locks.get(cls, set())
        inherited = _inherited_locks(methods, m, pkg, own_locks)
        sites: list[_Site] = []
        for fi in methods:
            _collect_func(m, fi, pkg, inherited[id(fi.node)], [],
                          sites, True, set(), own_locks)
        # init facts: attr -> (kind, def line)
        init_info: dict[str, tuple[str, int]] = {}
        for fi in methods:
            if fi.name != "__init__":
                continue
            for n in ast.walk(fi.node):
                targets, value = [], None
                if isinstance(n, ast.Assign):
                    targets, value = n.targets, n.value
                elif isinstance(n, ast.AnnAssign) and \
                        n.value is not None:
                    targets, value = [n.target], n.value
                for t in targets:
                    if isinstance(t, ast.Attribute) and \
                            isinstance(t.value, ast.Name) and \
                            t.value.id == "self":
                        kind = _init_kind(value, lock_owners)
                        prev = init_info.get(t.attr)
                        if prev is None or prev[0] != "sync":
                            init_info[t.attr] = (kind, n.lineno)
        by_attr: dict[str, list[_Site]] = {}
        for s in sites:
            by_attr.setdefault(s.key, []).append(s)
        for attr, attr_sites in sorted(by_attr.items()):
            key = f"{mod}.{cls}.{attr}"
            if attr in own_locks or attr in m.locks:
                continue               # the lock itself
            kind, def_line = init_info.get(attr, ("other", 0))
            writes = [s for s in attr_sites if not s.in_init
                      and (s.kind == "write"
                           or (s.kind == "mutate"
                               and kind == "container"))]
            if not writes:
                continue               # init-confined (or sync-managed)
            if def_line:
                sup = m.suppression_for(def_line, RULE)
                if sup is not None:
                    sup.used = True    # declared GIL-atomic/confined
                    continue
            reads = [s for s in attr_sites
                     if not s.in_init and s.kind == "read"]
            relevant = writes + reads
            common = frozenset.intersection(
                *[s.locks for s in relevant])
            if common:
                continue
            site = next((s for s in writes if not s.locks),
                        next((s for s in reads if not s.locks),
                             writes[0]))
            out.append(Finding(
                RULE, m.relpath, site.line, site.col,
                f"`{key}` has no common lockset across its "
                f"{len(writes)} write / {len(reads)} read site(s) "
                f"(class is shared: {why}) — unlocked {site.kind} in "
                f"{site.func.qualname}. Guard every access with one "
                f"lock, confine writes to __init__, or declare the "
                f"attribute at its definition line"))
    return out


def _check_globals(m: Module, pkg: Package,
                   lock_owners: set[str]) -> list[Finding]:
    out: list[Finding] = []
    mod = _mod_tag(m)
    # module-level bindings + their init classification
    globals_: dict[str, tuple[str, int]] = {}

    def harvest(stmts):
        for node in stmts:
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        globals_.setdefault(
                            t.id, (_init_kind(node.value, lock_owners),
                                   node.lineno))
            elif isinstance(node, ast.AnnAssign) and \
                    isinstance(node.target, ast.Name):
                kind = (_init_kind(node.value, lock_owners)
                        if node.value is not None else "other")
                globals_.setdefault(node.target.id, (kind, node.lineno))
            elif isinstance(node, (ast.If, ast.Try)):
                harvest(getattr(node, "body", []))
                harvest(getattr(node, "orelse", []))
                harvest(getattr(node, "finalbody", []))

    harvest(m.tree.body)
    if not globals_:
        return out
    names = set(globals_)
    cls_locks = _class_locks(m)

    def own(fi):
        return cls_locks.get(fi.class_name or "", set())

    inherited = _locked_inheritance(
        m, pkg, m.functions,
        {fi.name: fi for fi in m.functions
         if fi.class_name is None and fi.name.endswith("_locked")},
        False, names, own)
    sites: list[_Site] = []
    for fi in m.functions:
        # methods can mutate module globals too — collect everywhere
        _collect_func(m, fi, pkg, inherited[id(fi.node)], [], sites,
                      False, names, own(fi))
    by_name: dict[str, list[_Site]] = {}
    for s in sites:
        kind, _ln = globals_[s.key]
        if s.kind == "mutate" and kind != "container":
            continue   # method call on a synchronized/opaque object
        by_name.setdefault(s.key, []).append(s)
    for name, wsites in sorted(by_name.items()):
        kind, def_line = globals_[name]
        sup = m.suppression_for(def_line, RULE)
        if sup is not None:
            sup.used = True
            continue
        common = frozenset.intersection(*[s.locks for s in wsites])
        if common:
            continue
        site = next((s for s in wsites if not s.locks), wsites[0])
        out.append(Finding(
            RULE, m.relpath, site.line, site.col,
            f"module global `{mod}.{name}` is written from function "
            f"scope with no common lockset ({len(wsites)} write "
            f"site(s)) — unlocked {site.kind} in {site.func.qualname}."
            f" Guard the writes with one module lock or declare the "
            f"global at its definition line"))
    return out
