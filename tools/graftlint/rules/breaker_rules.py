"""Rule family 1 — breaker-hold pairing.

Every `CircuitBreaker.add_estimate` reserves bytes that some exit path
must give back; PR 4 (collect_segment_result) and PR 5 both shipped a
leak before growing their finally-release. The rule demands that each
`add_estimate` call site exhibit ONE of the structural release shapes
the codebase already uses:

  * a `with breaker.hold(n):` block (the utils/breaker.Hold fast path);
  * a Try — containing or following the call — whose `finally` releases,
    or whose except handler releases AND re-raises;
  * a `weakref.finalize(obj, breaker.release, n)` GC backstop;
  * transfer into a hold wrapper (`_gc_backstop(obj, hold)`,
    `*Hold*(...)`, `.hold(`) that owns the release;
  * a matching `.release(` as the IMMEDIATELY next statement (nothing
    can raise in between);
  * the class-managed pattern: the enclosing class defines a `release`
    method (ResidentEntry, CircuitBreaker.hold's Hold object).

A `.hold(` call whose result is discarded is also flagged — a Hold
nobody retains can only be released by GC, which is exactly the lazy
backstop the rule exists to forbid as the only path.
"""

from __future__ import annotations

import ast

from ..core import Finding, Package, call_name, calls_in, dotted

RULE = "breaker-hold"


def _receiver(call: ast.Call) -> str:
    """Textual receiver of an attribute call: `b.add_estimate(n)` -> 'b',
    `breaker_service().breaker("x").add_estimate(n)` -> the full chain."""
    if isinstance(call.func, ast.Attribute):
        return dotted(call.func.value) or ast.dump(call.func.value)
    return ""


def _release_calls(node: ast.AST) -> list[ast.Call]:
    return [c for c in calls_in(node)
            if call_name(c).split(".")[-1] == "release"]


def _has_release_for(node: ast.AST, recv: str) -> bool:
    """A .release( whose receiver matches (or any, when the estimate
    receiver is a call chain that cannot be name-matched textually)."""
    for c in _release_calls(node):
        r = _receiver(c)
        if not recv or not r or r == recv or "()" in recv or "()" in r:
            return True
    return False


def _try_protects(try_node: ast.Try, recv: str) -> bool:
    if any(_has_release_for(s, recv) for s in try_node.finalbody):
        return True
    for handler in try_node.handlers:
        body = ast.Module(body=handler.body, type_ignores=[])
        if any(_has_release_for(s, recv) for s in handler.body) and any(
                isinstance(n, ast.Raise) for n in ast.walk(body)):
            return True
    return False


def _finalize_registers_release(call: ast.Call) -> bool:
    """weakref.finalize(obj, X.release, n) — the GC-backstop shape."""
    if call_name(call).split(".")[-1] != "finalize":
        return False
    return any(isinstance(a, ast.Attribute) and a.attr == "release"
               for a in call.args)


def _transfers_to_hold(call: ast.Call) -> bool:
    base = call_name(call).split(".")[-1]
    return "Hold" in base or base == "hold" or "backstop" in base


def check(pkg: Package) -> list[Finding]:
    findings: list[Finding] = []
    for m in pkg.modules:
        for fi in m.functions:
            stmts = list(ast.walk(fi.node))
            tries = [n for n in stmts if isinstance(n, ast.Try)]
            for call in calls_in(fi.node):
                base = call_name(call).split(".")[-1]
                if base == "hold":
                    findings.extend(_check_hold(m, fi, call))
                if base != "add_estimate":
                    continue
                recv = _receiver(call)
                if _protected(fi, call, recv, tries):
                    continue
                findings.append(Finding(
                    RULE, m.relpath, call.lineno, call.col_offset,
                    f"breaker estimate `{recv or '?'}.add_estimate(...)` "
                    f"in {fi.qualname} has no release reachable on all "
                    f"exits — wrap in try/finally, use "
                    f"`with breaker.hold(n):`, or attach a GC-backstopped "
                    f"hold"))
    return findings


def _next_acquisition_line(fi, call: ast.Call) -> float:
    """Line of the NEXT breaker acquisition (add_estimate/.hold) after
    `call` in the function. Protections found past it belong to THAT
    estimate, not this one — without the bound, any unrelated later
    hold/finalize in the same function would mask a genuine leak (the
    exact regression class this rule exists to catch)."""
    nxt = float("inf")
    for c in calls_in(fi.node):
        if c is call:
            continue
        if call_name(c).split(".")[-1] in ("add_estimate", "hold") \
                and c.lineno > call.lineno:
            nxt = min(nxt, c.lineno)
    return nxt


def _protected(fi, call: ast.Call, recv: str, tries: list[ast.Try]) -> bool:
    bound = _next_acquisition_line(fi, call)
    # (a) a protecting Try containing the call, or starting after it
    # but before the next acquisition claims the protection slot
    for t in tries:
        contains = any(n is call for n in ast.walk(t))
        if (contains or call.lineno <= t.lineno < bound) \
                and _try_protects(t, recv):
            return True
    after = [n for n in ast.walk(fi.node)
             if isinstance(n, ast.stmt)
             and call.lineno < n.lineno < bound]
    # (b) GC backstop or hold-wrapper transfer before the next
    # acquisition
    for s in after:
        for c in calls_in(s) + ([s.value] if isinstance(s, ast.Expr)
                                and isinstance(s.value, ast.Call) else []):
            if _finalize_registers_release(c) or _transfers_to_hold(c):
                return True
    # (c) matching release as the immediately-next statement
    nxt = _next_sibling(fi.node, call)
    if nxt is not None and _has_release_for(nxt, recv):
        return True
    # (d) class-managed holds: the enclosing class owns a release()
    if fi.class_name:
        for other in fi.module.by_name.get("release", []):
            if other.class_name == fi.class_name:
                return True
        for other in fi.module.functions:
            if other.class_name == fi.class_name and other is not fi \
                    and _has_release_for(other.node, ""):
                return True
    return False


def _next_sibling(func: ast.FunctionDef, call: ast.Call) -> ast.stmt | None:
    """Statement right after the INNERMOST statement containing `call`
    in its own block (the outer containing statements would return
    their siblings instead, missing an immediate release inside a
    nested if/try)."""
    best: tuple[int, ast.stmt | None] | None = None
    for node in ast.walk(func):
        for attr in ("body", "orelse", "finalbody"):
            blk = getattr(node, attr, None)
            if not isinstance(blk, list):
                continue
            for i, stmt in enumerate(blk):
                if isinstance(stmt, ast.stmt) and \
                        any(n is call for n in ast.walk(stmt)):
                    nxt = blk[i + 1] if i + 1 < len(blk) else None
                    if best is None or stmt.lineno >= best[0]:
                        best = (stmt.lineno, nxt)
    return best[1] if best else None


def _check_hold(m, fi, call: ast.Call) -> list[Finding]:
    """`.hold(` structural fast path: the Hold must be retained — used
    as a `with` context, assigned, or passed along — never discarded."""
    stmt = _containing_stmt(fi.node, call)
    if stmt is None:
        return []
    if isinstance(stmt, ast.With) and any(
            any(n is call for n in ast.walk(item.context_expr))
            for item in stmt.items):
        return []
    if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.Return)):
        return []
    if isinstance(stmt, ast.Expr) and stmt.value is call:
        return [Finding(
            RULE, m.relpath, call.lineno, call.col_offset,
            f"hold() result discarded in {fi.qualname} — only GC could "
            f"ever release it; use `with ...hold(n):` or keep the Hold")]
    return []


def _containing_stmt(func: ast.FunctionDef, call: ast.Call):
    """Innermost statement containing `call`."""
    best = None
    for node in ast.walk(func):
        if isinstance(node, ast.stmt) and \
                any(n is call for n in ast.walk(node)):
            if best is None or node.lineno >= best.lineno:
                best = node
    return best
