"""Rule family 4 — recompile hazards.

Three shapes of "this call will compile more programs than anyone
budgeted for":

  * an UNHASHABLE static argument (list/dict/set literal or
    comprehension) passed to a jitted callable's static argname —
    crashes at best, and a converted-to-tuple-per-request variant
    recompiles per request;
  * a REQUEST-VARYING static: a static argname fed from wall-clock,
    RNG, uuid, or id() — every call mints a fresh compile key;
  * an UNBUCKETED size: an integer reaching a pinned/AOT entry point's
    `k`/batch parameter, or a compiled-program cache-key constructor
    (`_resident_entry_key`, the mesh `_compiled`), without passing
    through the pow2 bucketing helpers (`next_pow2`) anywhere on its
    def-use chain. PR 5's k-bucketing regression is the ancestor
    violation. The chase is interprocedural (depth-limited through
    call sites) and deliberately forgiving: only a chain that
    PROVABLY bottoms out in a raw request value (len(...), .get(...),
    dict subscript) fires.
"""

from __future__ import annotations

import ast

from ..core import Finding, Package, FuncInfo, calls_in, call_name

RULE = "recompile-hazard"

_BUCKETERS = {"next_pow2", "pow2_bucket", "bucket_pow2"}
# parameter names that denote compile-key sizes at AOT boundaries;
# ck (per-tile selection depth) and chunk_tiles (stepped chunk span)
# joined when the chunked pallas_call entry points grew static shapes
# derived from them; tile / chunk_cap / n_slots joined with the tiered
# chunk programs (PR 11) — the paged tile capacity is a static shape,
# so it must arrive pow2-bucketed (index/tiering.chunk_tiles does)
# n_clusters / nprobe / cluster_cap joined with the IVF probe (PR 14):
# all three are static shapes of the probe program (ops/ann.ivf_topk) —
# a raw sqrt(N) cluster count or a request-supplied nprobe would mint a
# compile key per segment/request (index/ann pow2-buckets all three,
# the pad_delta_shapes convention)
# batch_cap / term_cap / vocab_buckets joined with the device-parallel
# builder (ISSUE 16): the builder's static shapes — occurrence batch,
# tile_max term rows, term-id scatter width — are content-proportional
# per segment, so each must arrive pow2-bucketed (index/devbuild
# next_pow2's all three) or every refresh would mint fresh sort/pack
# programs
# pos_width / pos_p joined with positional scoring (ISSUE 20): the
# per-slot position capacity P and the widest positional slab L*P are
# static shapes of the positional kernels and the mesh pack — both
# must arrive pow2-bucketed (index/segment buckets P at build time,
# parallel/distributed.PackSpec next_pow2's pos_p) or come straight
# off an array shape; a raw request-derived width would mint one
# Mosaic program per phrase length
_SIZE_PARAMS = {"k", "k_res", "k_eff", "b", "b_pad", "b_loc", "batch",
                "ck", "chunk_tiles", "tile", "chunk_cap", "n_slots",
                "n_clusters", "nprobe", "cluster_cap",
                "batch_cap", "term_cap", "vocab_buckets",
                "pos_width", "pos_p"}
# cache-key constructors guarded in addition to jitted entry points —
# the chunked Pallas bundle entries mint one Mosaic program per
# (clauses, k, chunk span) and must only ever see bucketed sizes.
# The streaming write path's (base_generation, delta_epoch) key
# constructors joined in PR 9: a raw size reaching a pack tune/resident
# key would mint one cache entry per request AND defeat the
# zero-retune-refresh invariant (the delta-extent bucket must come
# through next_pow2, as Segment.cache_key does)
_CACHE_KEY_FUNCS = {"_resident_entry_key", "_compiled",
                    "fused_topk_bundle_pallas",
                    "match_mask_bundle_pallas", "_bundle_chunk_call",
                    "_pack_tune_key", "_pack_resident_backend",
                    "_execute_pack_resident",
                    # tiered chunk walk (PR 11): the chunk programs'
                    # tile/chunk_tiles statics mint one program per
                    # value — guard the non-jit driver entry too
                    "_execute_tiered", "_tiered_chunk_cols",
                    # positional admission (ISSUE 20): pos_width picks
                    # the compiled positional program family (and the
                    # VMEM gate), so the admission constructors only
                    # ever see shape-derived or bucketed widths
                    "_bundle_pallas_ok", "_bundle_pallas_reason"}
_VARYING = {"time.time", "time.monotonic", "time.perf_counter",
            "random.random", "random.randint", "uuid.uuid4", "id"}
_UNHASHABLE = (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp,
               ast.SetComp, ast.GeneratorExp)
# raw request-value producers: a size chain ending here was never
# bucketed
_RAW_TAILS = {"len", "get", "count", "index"}

_CHASE_DEPTH = 2


def check(pkg: Package) -> list[Finding]:
    findings: list[Finding] = []
    for m in pkg.modules:
        for fi in m.functions:
            for call in calls_in(fi.node):
                name = call_name(call)
                if not name:
                    continue
                bare = name.split(".")[-1]
                jit = pkg.jit_info(m, name)
                is_lower = bare == "lower" and \
                    pkg.jit_info(m, ".".join(name.split(".")[:-1])) \
                    is not None
                if jit is not None and jit.static_argnames:
                    findings.extend(_check_statics(m, fi, call, jit))
                if is_lower:
                    jit = pkg.jit_info(m, ".".join(name.split(".")[:-1]))
                    findings.extend(_check_statics(m, fi, call, jit))
                # unbucketed sizes into AOT boundaries / cache keys
                target: FuncInfo | None = None
                if jit is not None or is_lower or bare in _CACHE_KEY_FUNCS:
                    target = pkg.resolve(
                        m, name if not is_lower
                        else ".".join(name.split(".")[:-1]), fi)
                if target is not None:
                    findings.extend(_check_buckets(
                        pkg, m, fi, call, target))
    return findings


def _check_statics(m, fi, call: ast.Call, jit) -> list[Finding]:
    out = []
    for kw in call.keywords:
        if kw.arg not in (jit.static_argnames or ()):
            continue
        if isinstance(kw.value, _UNHASHABLE):
            out.append(Finding(
                RULE, m.relpath, kw.value.lineno, kw.value.col_offset,
                f"unhashable static argument `{kw.arg}` to jitted "
                f"`{call_name(call)}` in {fi.qualname} — statics must "
                f"hash stably (use a tuple built at bind time)"))
            continue
        for c in ast.walk(kw.value):
            if isinstance(c, ast.Call) and call_name(c) in _VARYING:
                out.append(Finding(
                    RULE, m.relpath, c.lineno, c.col_offset,
                    f"request-varying static `{kw.arg}` "
                    f"(`{call_name(c)}()`) to jitted "
                    f"`{call_name(call)}` in {fi.qualname} — every call "
                    f"mints a fresh compile key"))
    return out


def _check_buckets(pkg, m, fi, call, target: FuncInfo) -> list[Finding]:
    out = []
    params = target.params()
    bound: list[tuple[str, ast.AST]] = []
    for i, a in enumerate(call.args):
        pi = i + (1 if params and params[0] == "self" else 0)
        if pi < len(params):
            bound.append((params[pi], a))
    for kw in call.keywords:
        if kw.arg:
            bound.append((kw.arg, kw.value))
    for pname, expr in bound:
        if pname not in _SIZE_PARAMS:
            continue
        verdict = _bucketed(pkg, fi, expr, _CHASE_DEPTH)
        if verdict is False:
            out.append(Finding(
                RULE, m.relpath, expr.lineno, expr.col_offset,
                f"size `{pname}` reaching compiled-program boundary "
                f"`{call_name(call)}` in {fi.qualname} without pow2 "
                f"bucketing — raw request sizes mint a compile key per "
                f"request (route through next_pow2)"))
    return out


def _bucketed(pkg, fi: FuncInfo, expr: ast.AST, depth: int) -> bool | None:
    """True = provably bucketed/constant; False = provably raw;
    None = unknown (never fires — precision over recall)."""
    for n in ast.walk(expr):
        if isinstance(n, ast.Call) and \
                call_name(n).split(".")[-1] in _BUCKETERS:
            return True
    if isinstance(expr, ast.Constant):
        return True
    if isinstance(expr, ast.Call):
        if call_name(expr).split(".")[-1] in _RAW_TAILS:
            return False
        if call_name(expr).split(".")[-1] in ("min", "max"):
            sub = [_bucketed(pkg, fi, a, depth) for a in expr.args]
            if any(s is True for s in sub):
                return True
            if any(s is False for s in sub):
                return False
        return None
    if isinstance(expr, ast.IfExp):
        sub = [_bucketed(pkg, fi, e, depth)
               for e in (expr.body, expr.orelse)]
        if False in sub:
            return False
        if all(s is True for s in sub):
            return True
        return None
    if isinstance(expr, ast.BinOp):
        sub = [_bucketed(pkg, fi, e, depth)
               for e in (expr.left, expr.right)]
        if False in sub:
            return False
        return None
    if isinstance(expr, ast.Subscript) and \
            isinstance(expr.value, ast.Name):
        return False if _is_request_dict(fi, expr.value.id) else None
    if isinstance(expr, ast.Name):
        return _chase_name(pkg, fi, expr.id, depth)
    return None


def _is_request_dict(fi: FuncInfo, name: str) -> bool:
    """Heuristic: subscripting a parameter named like a request body."""
    return name in ("body", "request", "req") and name in fi.params()


def _chase_name(pkg, fi: FuncInfo, name: str, depth: int) -> bool | None:
    # local assignments win over the parameter of the same name
    assigns = []
    for n in ast.walk(fi.node):
        if isinstance(n, ast.Assign):
            for t in n.targets:
                if isinstance(t, ast.Name) and t.id == name:
                    assigns.append(n.value)
        elif isinstance(n, ast.AugAssign) and \
                isinstance(n.target, ast.Name) and n.target.id == name:
            assigns.append(n.value)
    if assigns:
        sub = [_bucketed(pkg, fi, a, depth) for a in assigns]
        if all(s is True for s in sub):
            return True
        if False in sub:
            return False
        return None
    if name in fi.params():
        if depth <= 0:
            return None
        sites = pkg.call_sites(fi)
        if not sites:
            return None
        params = fi.params()
        verdicts = []
        for caller, call in sites:
            expr = None
            for i, a in enumerate(call.args):
                pi = i + (1 if params and params[0] == "self" else 0)
                if pi < len(params) and params[pi] == name:
                    expr = a
            for kw in call.keywords:
                if kw.arg == name:
                    expr = kw.value
            if expr is not None:
                verdicts.append(_bucketed(pkg, caller, expr, depth - 1))
        if verdicts and all(v is True for v in verdicts):
            return True
        if False in verdicts:
            return False
    return None
