"""graftlint: device-path invariant analyzer for elasticsearch_tpu.

Seven rule families guard the lifecycle invariants the hot-path PRs
hand-maintained (and more than once violated before patching):

  breaker-hold        every breaker estimate releasable on all exits
  trace-purity        no host syncs/side effects inside traced code
                      (io_callback is the sanctioned bridge)
  donation-safety     donated wire buffers are dead after invocation
  recompile-hazard    statics must hash, vary per-plan not per-request,
                      and sizes must ride the pow2 buckets
  lock-discipline /   no blocking under dispatch/autotune/resident
  lock-order          locks, and the acquisition graph stays acyclic
  shared-state-race   Eraser-style lockset pass: cross-thread state
                      keeps a non-empty common lockset at every site
  collective-safety   SPMD contract: no collectives under divergent
                      control flow, branch parity, bound axis names,
                      and the stepped-deadline poll/verdict ordering

Runtime complements: utils/trace_guard.py (ES_TPU_TRACE_GUARD) and
utils/race_guard.py (ES_TPU_RACE_GUARD).

Run: python -m tools.graftlint elasticsearch_tpu
"""

from __future__ import annotations

from .core import (Finding, Package, apply_suppressions, load_baseline,
                   load_package, load_source, rule_counts, RULES)
from .rules import ALL_RULES


def lint(pkg: Package) -> list[Finding]:
    """All rule families over an index, suppressions applied."""
    findings: list[Finding] = []
    for rule in ALL_RULES:
        findings.extend(rule(pkg))
    return apply_suppressions(pkg, findings)


def lint_package(root: str, package: str) -> list[Finding]:
    return lint(load_package(root, package))


def lint_source(source: str, relpath: str = "<snippet>.py") -> list[Finding]:
    """Test-fixture entry: lint one source snippet."""
    return lint(load_source(source, relpath))


__all__ = ["Finding", "RULES", "lint", "lint_package", "lint_source",
           "load_baseline", "rule_counts"]
