"""CLI: python -m tools.graftlint <package> [options].

Exit status: 0 when every finding is suppressed (with reason) or
baselined; 1 otherwise. `--counts` prints the per-rule firing counts
(suppressed hits INCLUDED) as JSON — the CI diff surface; CI compares
against tools/graftlint/counts.json so a regression shows up as a
one-line diff, not a scroll.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from . import lint_package, load_baseline, rule_counts

_HERE = os.path.dirname(os.path.abspath(__file__))
DEFAULT_BASELINE = os.path.join(_HERE, "baseline.json")
DEFAULT_COUNTS = os.path.join(_HERE, "counts.json")


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="graftlint")
    ap.add_argument("package", help="package directory to analyze "
                                    "(e.g. elasticsearch_tpu)")
    ap.add_argument("--root", default=".", help="repo root")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="grandfathered-finding file (target: empty)")
    ap.add_argument("--counts", action="store_true",
                    help="print per-rule firing counts as JSON")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output: every finding "
                         "(suppressed included) + per-rule counts as "
                         "one JSON document on stdout")
    ap.add_argument("--write-counts", metavar="FILE", nargs="?",
                    const=DEFAULT_COUNTS,
                    help="write the counts JSON (default: the checked-in "
                         "counts.json)")
    ap.add_argument("--show-suppressed", action="store_true",
                    help="also print suppressed findings")
    args = ap.parse_args(argv)

    findings = lint_package(args.root, args.package)
    baseline = load_baseline(args.baseline)
    counts = rule_counts(findings)

    failing = [f for f in findings
               if not f.suppressed and f.key() not in baseline]
    grandfathered = sum(1 for f in findings
                        if not f.suppressed and f.key() in baseline)
    suppressed = sum(1 for f in findings if f.suppressed)
    if args.json:
        # the CI-facing contract: one document, stable keys, findings
        # in (path, line, rule) order — diffable and jq-able, so
        # counts.json regeneration and review stop being hand-edited
        print(json.dumps({
            "findings": [{
                "rule": f.rule, "path": f.path, "line": f.line,
                "col": f.col, "message": f.message,
                "suppressed": f.suppressed, "reason": f.reason,
                "baselined": (not f.suppressed
                              and f.key() in baseline),
            } for f in findings],
            "counts": counts,
            "failing": len(failing),
            "suppressed": suppressed,
            "baselined": grandfathered,
        }, indent=2, sort_keys=False))
    else:
        shown = findings if args.show_suppressed else failing
        for f in shown:
            print(f.render())
    if args.counts and not args.json:
        # under --json the counts are embedded in the one document —
        # a second JSON object would break json.loads/jq consumers
        print(json.dumps(counts, indent=0, sort_keys=True))
    if args.write_counts:
        with open(args.write_counts, "w", encoding="utf-8") as fh:
            json.dump(counts, fh, indent=2, sort_keys=True)
            fh.write("\n")
    print(f"graftlint: {len(failing)} failing, {suppressed} suppressed, "
          f"{grandfathered} baselined "
          f"({sum(counts.values())} total rule firings)", file=sys.stderr)
    return 1 if failing else 0


if __name__ == "__main__":
    sys.exit(main())
