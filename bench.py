"""Benchmarks for all five BASELINE.json configs, TPU vs CPU baselines.

Prints ONE JSON line PER METRIC (5 lines):

  {"metric": "http_logs_bm25_qps",          "value": ..., "unit": "qps",
   "vs_baseline": ..., "p50_ms": ..., "p99_ms": ...}
  {"metric": "msmarco_bool_bm25_qps",       ...}
  {"metric": "nyc_taxis_terms_agg_ms_per_query",  "unit": "ms", ...}
  {"metric": "nyc_taxis_date_histogram_ms_per_query", ...}
  {"metric": "msmarco_knn_rescore_qps",     ...}

`vs_baseline` is always "x times faster than the CPU baseline":
tpu_qps / cpu_qps for throughput metrics, cpu_ms / tpu_ms for latency
metrics. Baselines are numpy implementations of the SAME algorithmic
family (eager-impact BM25, bincount aggs, exact-matmul kNN) with pinned
seeds, so the ratio isolates the hardware/XLA win and cannot drift run
to run the way a wall-clock-resampled baseline does.

On a TPU backend, configs [0] (http_logs match) and [1] (msmarco bool
must/should) additionally A/B the autotuned fused block-max score+top-k
path against the plain unfused XLA path ("fused_qps" / "xla_qps"
fields). On every backend they gate fused results on doc-id identity
with the unfused path, and EVERY executor workload reports a "fused"
block: admission rate with per-reason rejections, block-prune rate, and
the autotuner's backend choices.

Reference paths these mirror (BASELINE.md):
- BM25 + top-k: search/query/QueryPhase.java:92-168
- terms/date_histogram: bucket/terms/GlobalOrdinalsStringTermsAggregator
  .java:101-116, bucket/histogram/HistogramAggregator.java
- kNN+rescore: BASELINE.json configs[4]
"""

from __future__ import annotations

import json
import os
import random
import sys
import threading
import time

import numpy as np

N_DOCS = int(os.environ.get("BENCH_DOCS", 100_000))
BATCH = int(os.environ.get("BENCH_BATCH", 1024))
N_BATCHES = int(os.environ.get("BENCH_BATCHES", 8))
# HBM-resident analytics scale: Rally's nyc_taxis is ~165M rows; at 20M
# the corpus no longer fits CPU caches (where numpy bincount shines)
# while the TPU column scan barely notices — the scale the hardware
# comparison is honest at. CPU baselines run at the SAME row count.
TAXI_ROWS = int(os.environ.get("BENCH_TAXI_ROWS", 20_000_000))
TAXI_CARD = int(os.environ.get("BENCH_TAXI_CARD", 10_000))
AGG_REPS = int(os.environ.get("BENCH_AGG_REPS", 30))
# HBM-resident vector scale (msmarco-v2 is 138M passages; 50k fits in
# CPU cache). 1M x 256 = 0.5GB bf16 on device; the CPU baseline runs at
# the same scale.
KNN_DOCS = int(os.environ.get("BENCH_KNN_DOCS", 1_000_000))
KNN_DIM = int(os.environ.get("BENCH_KNN_DIM", 256))
KNN_BATCH = int(os.environ.get("BENCH_KNN_BATCH", 256))
TOP_K = 10

COMMON_WORDS = ["images", "french", "english", "venues", "tickets", "news",
                "sport", "history", "results", "teams", "athletes", "medal",
                "schedule", "village", "torch", "ceremony", "host", "city",
                "official", "site", "main", "index", "home", "photos",
                "stories", "accueil", "francais", "anglais", "cgi", "bin"]
METHODS = ["get", "post", "head"]
EXTS = ["html", "gif", "jpg", "cgi", "htm"]
VOCAB_SIZE = int(os.environ.get("BENCH_VOCAB", 4000))


def log(msg: str) -> None:
    print(f"# {msg}", file=sys.stderr)


def pcts(lat_ms: list[float]) -> tuple[float, float]:
    a = np.sort(np.asarray(lat_ms))
    return (float(np.percentile(a, 50)), float(np.percentile(a, 99)))


def throughput_and_latency(batches, dispatch, collect):
    """Two passes over `batches`:

    1. pipelined serving — dispatch EVERY batch async, then collect
       (host bind/dispatch overlaps in-flight device compute; what a
       served QPS number should measure), timed as a whole;
    2. per-batch round trips for p50/p99 latency.

    Returns (total_s, lat_ms list).
    """
    # best of two pipelined passes: the shared dev-tunnel device has
    # visible run-to-run contention; the faster pass is the truer
    # hardware number
    totals = []
    for _ in range(2):
        t_all = time.time()
        pending = [dispatch(b) for b in batches]
        for tok in pending:
            collect(tok)
        totals.append(time.time() - t_all)
    total_s = min(totals)
    lat = []
    for b in batches:
        t_b = time.time()
        collect(dispatch(b))
        lat.append((time.time() - t_b) * 1000.0)
    return total_s, lat


def best_time(fn) -> float:
    """min elapsed of two runs — the same best-of-2 discipline the
    pipelined device pass uses, so host contention strips from BOTH
    sides of every vs_baseline ratio."""
    ts = []
    for _ in range(2):
        t0 = time.time()
        fn()
        ts.append(time.time() - t0)
    return min(ts)


def _vocab() -> list[str]:
    return COMMON_WORDS + [f"p{i:05d}" for i in range(VOCAB_SIZE)]


def _fused_reset():
    from elasticsearch_tpu.search import executor as ex
    ex._fused_stats.reset()


def _fused_block() -> dict:
    """Per-workload fused-scoring report: admission rate (with
    per-reason rejections — WHY a plan fell back, and which fused-
    admitted shapes the PALLAS kernel could not serve), block-prune
    rate, the autotuner's backend choices, and the loss audit (shapes
    where the Pallas candidate lost to XLA by >10% — the ROADMAP item-3
    regression signal, gated in _loss_audit_gate). Callers
    _fused_reset() at workload start so the numbers are
    workload-scoped."""
    from elasticsearch_tpu.search import executor as ex
    stats = ex.fused_scoring_stats()
    return {"admission_rate": round(stats["admission"]["rate"], 4),
            "rejected": stats["admission"]["rejected"],
            "pallas_rejected": stats["admission"]["pallas_rejected"],
            "prune_rate": round(stats["prune_rate"], 4),
            "backend_choices": stats["backend_choices"],
            "loss_audit": stats["loss_audit"]}


def _loss_audit_gate(label: str) -> None:
    """HARD gate on real-TPU runs: no fused plan shape where the Pallas
    kernel was admitted as a candidate but lost to XLA by >10% in the
    autotuner's best-of-N. Off-TPU the kernel is never timed, so the
    audit is vacuously clean and the gate is a no-op."""
    import jax
    from elasticsearch_tpu.search import executor as ex
    if jax.default_backend() != "tpu":
        return
    audit = ex.fused_scoring_stats()["loss_audit"]
    if audit["count"]:
        raise AssertionError(
            f"autotuner loss-audit failed ({label}): pallas lost to "
            f"xla by >10% on {audit['count']} shape(s): "
            f"{audit['shapes']}")


def _with_fused_disabled(fn):
    """Run fn with ES_TPU_FUSED=0, restoring the prior env."""
    prior = os.environ.get("ES_TPU_FUSED")
    os.environ["ES_TPU_FUSED"] = "0"
    try:
        return fn()
    finally:
        if prior is None:
            os.environ.pop("ES_TPU_FUSED", None)
        else:
            os.environ["ES_TPU_FUSED"] = prior


def _fused_identity_gate(dispatch_sample, label: str,
                         top_k: int = TOP_K) -> dict | None:
    """Fused-vs-unfused gate over EVERY signature group of a sample
    batch: totals and doc ids must be identical, scores within 1e-5
    (ids are the acceptance contract; scores stay tolerant to FMA-
    contraction ulps across backends). Returns the workload-scoped
    fused report (captured BEFORE the unfused rerun records its own
    'disabled' rejections), or None when fusion is env-disabled.
    Raises when vacuous — nothing was admitted, so the gate proved
    nothing."""
    from elasticsearch_tpu.search import executor as ex
    from elasticsearch_tpu.search.executor import collect_segment_result
    if not ex.fused_enabled():
        return None

    def _collected():
        return [collect_segment_result(o, l, n_)
                for o, l, n_ in dispatch_sample()]

    res_f = _collected()
    fused_report = _fused_block()
    res_u = _with_fused_disabled(_collected)
    for (hits_f, _af), (hits_u, _au) in zip(res_f, res_u):
        ts_f, _tkf, ti_f, tt_f, _tmf = hits_f
        ts_u, _tku, ti_u, tt_u, _tmu = hits_u
        if not (tt_f == tt_u).all():
            raise AssertionError(f"fused/unfused total mismatch ({label})")
        for qi in range(ts_f.shape[0]):
            n_check = min(int(tt_u[qi]), top_k)
            if not (ti_f[qi][:n_check] == ti_u[qi][:n_check]).all():
                raise AssertionError(
                    f"fused/unfused doc-id mismatch ({label})")
            if not np.allclose(ts_f[qi][:n_check], ts_u[qi][:n_check],
                               atol=1e-5, rtol=1e-5):
                raise AssertionError(
                    f"fused/unfused score mismatch ({label})")
    stats = ex.fused_scoring_stats()
    if stats["dispatches"] <= 0:
        raise AssertionError(
            f"fused path was never admitted ({label}); the "
            "fused/unfused identity gate is vacuous")
    _loss_audit_gate(label)
    return fused_report


def _fused_tpu_ab(out: dict, measured_run, n_done: int) -> None:
    """TPU-only A/B: re-measure the workload with fusion AND the Pallas
    kernels disabled (the BENCH_r05 unfused-XLA lineage) and report
    fused_qps / xla_qps. One definition for every workload — the env
    save/restore + cache-clear choreography must not fork per bench."""
    import jax
    from elasticsearch_tpu.search import executor as ex
    from elasticsearch_tpu.ops import pallas_scoring as ps
    if jax.default_backend() != "tpu" or not ex.fused_enabled():
        return
    out["fused_qps"] = out["value"]
    prior_f = os.environ.get("ES_TPU_FUSED")
    prior_p = os.environ.get("ES_TPU_PALLAS")
    os.environ["ES_TPU_FUSED"] = "0"
    os.environ["ES_TPU_PALLAS"] = "0"
    ps.pallas_enabled.cache_clear()
    ex._segment_program_packed.clear_cache()
    try:
        measured_run()   # recompile + warm the unfused path
        other_s, _ = measured_run()
        out["xla_qps"] = round(n_done / other_s, 1)
    finally:
        for var, prior in (("ES_TPU_FUSED", prior_f),
                           ("ES_TPU_PALLAS", prior_p)):
            if prior is None:
                os.environ.pop(var, None)
            else:
                os.environ[var] = prior
        ps.pallas_enabled.cache_clear()
        ex._segment_program_packed.clear_cache()


def _zipf_weights(n: int) -> list[float]:
    w = [1.0 / (i + 3) ** 0.9 for i in range(n)]
    total = sum(w)
    return [x / total for x in w]


def make_corpus(n: int, seed: int = 42):
    rng = random.Random(seed)
    vocab = _vocab()
    weights = _zipf_weights(len(vocab))

    def pick():
        return rng.choices(vocab, weights=weights)[0]

    zipf_paths = [[pick() for _ in range(rng.randint(2, 5))]
                  + [rng.choice(EXTS)] for _ in range(max(n // 25, 400))]
    docs = []
    for i in range(n):
        p = zipf_paths[min(int(rng.paretovariate(1.2)) - 1,
                           len(zipf_paths) - 1)]
        msg = " ".join([rng.choice(METHODS)] + p
                       + [str(rng.choice([200, 200, 200, 404, 304]))])
        docs.append((str(i), {"message": msg,
                              "size": rng.randint(100, 100_000),
                              "status": str(rng.choice(
                                  [200, 200, 200, 404, 500]))}))
    return docs


def make_queries(n: int, seed: int = 7, k_max: int = 3):
    rng = random.Random(seed)
    vocab = _vocab()
    head = vocab[: max(len(vocab) // 8, 30)]
    weights = _zipf_weights(len(head))
    return [" ".join(rng.choices(head, weights=weights,
                                 k=rng.randint(1, k_max)))
            for _ in range(n)]


# ---------------------------------------------------------------------------
# CPU baseline: CSR eager-impact scorer (BM25S-style)
# ---------------------------------------------------------------------------


class CpuBM25:
    def __init__(self, seg, field: str = "message"):
        pf = seg.text[field]
        self.term_index = pf.term_index
        self.indptr = pf.indptr
        self.doc_ids = pf.doc_ids
        from elasticsearch_tpu.index.segment import BM25_K1, BM25_B, bm25_idf
        idf = bm25_idf(pf.df.astype(np.float64), pf.doc_count)
        k_d = BM25_K1 * (1 - BM25_B + BM25_B * pf.doc_len / pf.avg_len)
        imps = np.empty_like(pf.tfs, dtype=np.float32)
        for t in range(len(pf.terms)):
            s, e = int(pf.indptr[t]), int(pf.indptr[t + 1])
            tf = pf.tfs[s:e].astype(np.float64)
            imps[s:e] = idf[t] * tf * (BM25_K1 + 1.0) / (
                tf + k_d[pf.doc_ids[s:e]])
        self.imps = imps
        self.n = seg.capacity

    def _scores(self, qterms: list[str]) -> np.ndarray:
        scores = np.zeros(self.n, dtype=np.float32)
        for t in qterms:
            tid = self.term_index.get(t, -1)
            if tid < 0:
                continue
            s, e = int(self.indptr[tid]), int(self.indptr[tid + 1])
            if e - s < 2048:
                scores[self.doc_ids[s:e]] += self.imps[s:e]
            else:
                scores += np.bincount(self.doc_ids[s:e],
                                      weights=self.imps[s:e],
                                      minlength=self.n).astype(np.float32)
        return scores

    def search(self, qterms: list[str], k: int):
        scores = self._scores(qterms)
        idx = np.argpartition(scores, -k)[-k:]
        order = idx[np.argsort(-scores[idx], kind="stable")]
        return order, scores[order]

    def search_bool(self, must: list[str], should: list[str], k: int):
        """bool must (required, scored) + should (optional, scored)."""
        scores = self._scores(must + should)
        for t in must:
            tid = self.term_index.get(t, -1)
            mask = np.zeros(self.n, dtype=bool)
            if tid >= 0:
                s, e = int(self.indptr[tid]), int(self.indptr[tid + 1])
                mask[self.doc_ids[s:e]] = True
            scores = np.where(mask, scores, 0.0)
        idx = np.argpartition(scores, -k)[-k:]
        order = idx[np.argsort(-scores[idx], kind="stable")]
        return order, scores[order]


def build_segment(docs, mapping):
    from elasticsearch_tpu.index.mapping import MapperService
    from elasticsearch_tpu.index.segment import SegmentBuilder
    svc = MapperService(mapping=mapping)
    builder = SegmentBuilder()
    for did, d in docs:
        builder.add(svc.parse(did, d))
    seg = builder.build("bench")
    live = np.zeros(seg.capacity, dtype=bool)
    live[: seg.num_docs] = True
    return svc, seg, live


# ---------------------------------------------------------------------------
# config[0]: http_logs match BM25 QPS (+ pallas A/B on TPU)
# ---------------------------------------------------------------------------


def bench_http_logs() -> dict:
    import jax
    from elasticsearch_tpu.search.query_dsl import QueryParser
    from elasticsearch_tpu.search.executor import (
        QueryBinder, execute_segment_async, collect_segment_result)

    _fused_reset()
    t0 = time.time()
    docs = make_corpus(N_DOCS)
    svc, seg, live = build_segment(docs, {"properties": {
        "message": {"type": "text"},
        "size": {"type": "long"},
        "status": {"type": "keyword"}}})
    log(f"http_logs: {N_DOCS} docs, {len(seg.text['message'].terms)} "
        f"terms, built in {time.time()-t0:.1f}s")

    queries = make_queries(BATCH * (N_BATCHES + 2))
    parser = QueryParser(svc)
    binder = QueryBinder(seg, svc)

    def dispatch_batch(batch_queries):
        bounds = [binder.bind(parser.parse({"bool": {"should": [
            {"match": {"message": q}}], "minimum_should_match": 1}}))
            for q in batch_queries]
        sig_groups = {}
        for b in bounds:
            sig_groups.setdefault(b.signature(), []).append(b)
        return [execute_segment_async(seg, live, group, TOP_K)
                for group in sig_groups.values()]

    batches = [queries[(i + 2) * BATCH: (i + 3) * BATCH]
               for i in range(N_BATCHES)]

    def collect_all(outs):
        for out, lay, n in outs:
            collect_segment_result(out, lay, n)

    def measured_run():
        return throughput_and_latency(batches, dispatch_batch, collect_all)

    t0 = time.time()
    measured_run()  # warmup incl. compiles
    log(f"http_logs warmup (incl. compiles): {time.time()-t0:.1f}s")
    total_s, lat = measured_run()
    n_done = sum(len(b) for b in batches)
    qps = n_done / total_s
    p50, p99 = pcts(lat)

    # CPU baseline (pinned seed corpus/queries -> stable denominator)
    cpu = CpuBM25(seg)
    analyzer = svc.analysis.analyzer("standard")
    cpu_queries = queries[2 * BATCH: 2 * BATCH + 128]
    cpu_qps = len(cpu_queries) / best_time(
        lambda: [cpu.search(analyzer.analyze(q), TOP_K)
                 for q in cpu_queries])

    # matched-recall gate on a sample
    sample = batches[0][:8]
    out0, lay0, n0 = dispatch_batch(sample)[0]
    (ts, _tk, ti, tt, _tm), _aggs = collect_segment_result(out0, lay0, n0)
    for qi, q in enumerate(sample):
        cpu_ids, cpu_scores = cpu.search(analyzer.analyze(q), TOP_K)
        n_check = min(int(tt[qi]), TOP_K)
        if not np.allclose(ts[qi][:n_check], cpu_scores[:n_check],
                           rtol=1e-4):
            raise AssertionError(f"score mismatch for {q!r}")
        if n_check >= 2 and cpu_scores[0] - cpu_scores[1] > 1e-3 * abs(
                cpu_scores[0]) and int(ti[qi][0]) != int(cpu_ids[0]):
            raise AssertionError(f"top-doc mismatch for {q!r}")

    out = {"metric": "http_logs_bm25_qps", "value": round(qps, 1),
           "unit": "qps", "vs_baseline": round(qps / cpu_qps, 2),
           "p50_ms": round(p50, 1), "p99_ms": round(p99, 1)}

    # fused-vs-unfused identity gate (any backend) + workload report
    fused_report = _fused_identity_gate(
        lambda: dispatch_batch(sample), "http_logs")
    if fused_report is not None:
        out["fused"] = fused_report

    # fused-autotuned vs plain unfused XLA A/B (TPU only: the round-5
    # xla_qps lineage this PR's acceptance bar is measured against)
    from elasticsearch_tpu.search import executor as ex
    if jax.default_backend() == "tpu" and not ex.fused_enabled():
        # fusion disabled for the measured run: no fused number to A/B
        # against. The unfused run still uses the Pallas kernels unless
        # those were ALSO disabled — label the lineage accordingly
        from elasticsearch_tpu.ops import pallas_scoring as ps
        out["xla_qps" if not ps.pallas_enabled() else "pallas_qps"] = \
            out["value"]
    else:
        _fused_tpu_ab(out, measured_run, n_done)
    return out


# ---------------------------------------------------------------------------
# config[1]: msmarco-style bool must/should multi-term BM25 QPS
# ---------------------------------------------------------------------------


def bench_bool_msmarco() -> dict:
    import jax
    from elasticsearch_tpu.search.query_dsl import QueryParser
    from elasticsearch_tpu.search.executor import (
        QueryBinder, execute_segment_async, collect_segment_result)

    _fused_reset()
    n = max(N_DOCS // 2, 10_000)
    rng = random.Random(11)
    vocab = _vocab()
    weights = _zipf_weights(len(vocab))
    t0 = time.time()
    docs = []
    for i in range(n):
        # passage-like docs: 20-60 tokens
        words = rng.choices(vocab, weights=weights,
                            k=rng.randint(20, 60))
        docs.append((str(i), {"passage": " ".join(words)}))
    svc, seg, live = build_segment(docs, {"properties": {
        "passage": {"type": "text"}}})
    log(f"msmarco: {n} passages, {len(seg.text['passage'].terms)} terms, "
        f"built in {time.time()-t0:.1f}s")

    rngq = random.Random(13)
    head = vocab[: max(len(vocab) // 8, 30)]
    wts = _zipf_weights(len(head))
    pairs = []
    for _ in range(BATCH // 2 * (N_BATCHES + 1)):
        must = rngq.choices(head, weights=wts, k=1)
        should = rngq.choices(head, weights=wts, k=rngq.randint(2, 4))
        pairs.append((must, should))

    parser = QueryParser(svc)
    binder = QueryBinder(seg, svc)

    def body(must, should):
        return {"bool": {
            "must": [{"match": {"passage": t}} for t in must],
            "should": [{"match": {"passage": t}} for t in should]}}

    def dispatch(batch):
        bounds = [binder.bind(parser.parse(body(m, s_)))
                  for m, s_ in batch]
        groups = {}
        for b in bounds:
            groups.setdefault(b.signature(), []).append(b)
        return [execute_segment_async(seg, live, g, TOP_K)
                for g in groups.values()]

    bsz = BATCH // 2
    batches = [pairs[(i + 1) * bsz: (i + 2) * bsz]
               for i in range(N_BATCHES)]

    def collect_all(outs):
        for out, lay, n_ in outs:
            collect_segment_result(out, lay, n_)

    def run():
        return throughput_and_latency(batches, dispatch, collect_all)

    t0 = time.time()
    run()
    log(f"msmarco warmup: {time.time()-t0:.1f}s")
    total_s, lat = run()
    n_done = sum(len(b) for b in batches)
    qps = n_done / total_s
    p50, p99 = pcts(lat)

    cpu = CpuBM25(seg, "passage")
    analyzer = svc.analysis.analyzer("standard")
    cpu_pairs = pairs[:96]
    cpu_qps = len(cpu_pairs) / best_time(
        lambda: [cpu.search_bool(
            [w for t in m for w in analyzer.analyze(t)],
            [w for t in s_ for w in analyzer.analyze(t)], TOP_K)
            for m, s_ in cpu_pairs])
    out = {"metric": "msmarco_bool_bm25_qps", "value": round(qps, 1),
           "unit": "qps", "vs_baseline": round(qps / cpu_qps, 2),
           "p50_ms": round(p50, 1), "p99_ms": round(p99, 1)}

    # fused-vs-unfused identity gate (any backend): the block-max-WAND
    # bool engine must return the SAME doc ids and totals as the
    # unfused full-matrix path — checked over every signature group of
    # a sample batch — plus the workload fused report
    fused_report = _fused_identity_gate(
        lambda: dispatch(batches[0][:16]), "msmarco_bool")
    if fused_report is not None:
        out["fused"] = fused_report

    # fused-autotuned vs plain unfused XLA A/B (TPU only) — the
    # msmarco_bool acceptance bar is measured against BENCH_r05's
    # unfused lineage
    _fused_tpu_ab(out, run, n_done)
    return out


def _with_positional_disabled(fn):
    """Run fn with ES_TPU_POSITIONAL=0 (phrase/span/BM25F served by the
    host oracle, search/phrase.py), restoring the prior env."""
    prior = os.environ.get("ES_TPU_POSITIONAL")
    os.environ["ES_TPU_POSITIONAL"] = "0"
    try:
        return fn()
    finally:
        if prior is None:
            os.environ.pop("ES_TPU_POSITIONAL", None)
        else:
            os.environ["ES_TPU_POSITIONAL"] = prior


def bench_phrase_heavy() -> dict:
    """Positional scoring on device (ISSUE 20): an msmarco-shaped
    workload where every query carries a positional clause — exact and
    sloppy phrases, ordered/unordered span_near, and multi_match
    cross_fields (BM25F) over title+passage. A/B is ES_TPU_POSITIONAL:
    on = phrase/span/BM25F evaluated per tile inside the fused bundle
    engines against the fwd_pos column family; off = the host oracle
    loops (search/phrase.py). The A/B is identity-gated per query, the
    run hard-fails if the device positional path was never dispatched,
    and on TPU the fused p50 must come in at <= 0.5x the host oracle's.
    """
    import jax
    from elasticsearch_tpu.search.query_dsl import QueryParser
    from elasticsearch_tpu.search.executor import (
        QueryBinder, execute_segment_async, collect_segment_result)
    from elasticsearch_tpu.search import executor as ex

    _fused_reset()
    n = max(N_DOCS // 2, 10_000)
    rng = random.Random(17)
    vocab = _vocab()
    weights = _zipf_weights(len(vocab))
    t0 = time.time()
    docs, texts = [], []
    for i in range(n):
        words = rng.choices(vocab, weights=weights,
                            k=rng.randint(20, 60))
        title = rng.choices(vocab, weights=weights,
                            k=rng.randint(3, 8))
        texts.append(words)
        docs.append((str(i), {"title": " ".join(title),
                              "passage": " ".join(words)}))
    svc, seg, live = build_segment(docs, {"properties": {
        "title": {"type": "text"}, "passage": {"type": "text"}}})
    pf = seg.text["passage"]
    log(f"phrase_heavy: {n} passages, pos_width={pf.pos_width}, "
        f"built in {time.time()-t0:.1f}s")

    # queries sampled from real passages so phrases actually land:
    # 40% match_phrase (exact + sloppy), 30% span_near, 30% BM25F
    rngq = random.Random(19)
    bodies = []
    for _ in range(BATCH // 2 * (N_BATCHES + 1)):
        src = texts[rngq.randrange(len(texts))]
        j = rngq.randrange(len(src) - 3)
        r = rngq.random()
        if r < 0.4:
            ln = 3 if rngq.random() < 0.3 else 2
            bodies.append({"match_phrase": {"passage": {
                "query": " ".join(src[j:j + ln]),
                "slop": rngq.choice([0, 0, 1, 2])}}})
        elif r < 0.7:
            bodies.append({"span_near": {"clauses": [
                {"span_term": {"passage": src[j]}},
                {"span_term": {"passage": src[j + 2]}}],
                "slop": rngq.choice([2, 3, 4]),
                "in_order": rngq.random() < 0.5}})
        else:
            bodies.append({"multi_match": {
                "query": " ".join(src[j:j + 2]),
                "type": "cross_fields",
                "fields": ["title^2", "passage"]}})

    parser = QueryParser(svc)
    binder = QueryBinder(seg, svc)

    def dispatch(batch):
        bounds = [binder.bind(parser.parse(b)) for b in batch]
        groups = {}
        for b in bounds:
            groups.setdefault(b.signature(), []).append(b)
        return [execute_segment_async(seg, live, g, TOP_K)
                for g in groups.values()]

    bsz = BATCH // 2
    batches = [bodies[(i + 1) * bsz: (i + 2) * bsz]
               for i in range(N_BATCHES)]

    def collect_all(outs):
        for out_, lay, n_ in outs:
            collect_segment_result(out_, lay, n_)

    def run():
        return throughput_and_latency(batches, dispatch, collect_all)

    t0 = time.time()
    run()
    log(f"phrase_heavy warmup: {time.time()-t0:.1f}s")
    total_s, lat = run()
    n_done = sum(len(b) for b in batches)
    p50, p99 = pcts(lat)

    # hard gate: the workload must actually exercise the device
    # positional path — a silent all-host-fallback bench would report a
    # meaningless A/B
    stats = ex.fused_scoring_stats()
    if stats["positional"]["dispatches"] <= 0:
        raise AssertionError(
            "phrase_heavy: zero fused positional dispatches — every "
            "query fell back to the host oracle "
            f"(fallbacks={stats['admission']['positional_fallbacks']})")
    pos_report = {
        "dispatches": stats["positional"]["dispatches"],
        "tiles": stats["positional"]["tiles"],
        "prune_rate": round(stats["positional"]["prune_rate"], 4),
        "admitted": stats["admission"]["positional_admitted"],
        "fallbacks": stats["admission"]["positional_fallbacks"]}

    # per-query identity gate vs the host oracle (grouping differs
    # between the two binders, so compare one query at a time)
    def _per_query(sample):
        out_ = []
        for b in sample:
            res = execute_segment_async(
                seg, live, [binder.bind(parser.parse(b))], TOP_K)
            out_.append(collect_segment_result(*res))
        return out_

    sample = batches[0][:24]
    res_f = _per_query(sample)
    res_h = _with_positional_disabled(lambda: _per_query(sample))
    for qi, ((hits_f, _af), (hits_h, _ah)) in enumerate(zip(res_f, res_h)):
        ts_f, _tkf, ti_f, tt_f, _tmf = hits_f
        ts_h, _tkh, ti_h, tt_h, _tmh = hits_h
        if not (tt_f == tt_h).all():
            raise AssertionError(
                f"phrase_heavy: device/host total mismatch on "
                f"{sample[qi]}")
        n_check = min(int(tt_h[0]), TOP_K)
        if not (ti_f[0][:n_check] == ti_h[0][:n_check]).all() or \
                not (ts_f[0][:n_check] == ts_h[0][:n_check]).all():
            raise AssertionError(
                f"phrase_heavy: device/host hit mismatch on "
                f"{sample[qi]}")

    # host-oracle A/B: the same measured run with ES_TPU_POSITIONAL=0
    def _host_run():
        _with_positional_disabled(run)              # warm the host path
        other_s, lat_h = _with_positional_disabled(run)
        return pcts(lat_h)[0]

    host_p50 = _host_run()
    out = {"metric": "phrase_heavy_p50_ms", "value": round(p50, 1),
           "unit": "ms", "vs_baseline": round(host_p50 / p50, 2),
           "p50_ms": round(p50, 1), "p99_ms": round(p99, 1),
           "qps": round(n_done / total_s, 1),
           "host_oracle_p50_ms": round(host_p50, 1),
           "positional": pos_report}
    # acceptance bar (TPU only — on CPU the "device" path is XLA
    # emulation and the bar says nothing): fused p50 <= 0.5x host
    if jax.default_backend() == "tpu" and p50 > 0.5 * host_p50:
        raise AssertionError(
            f"phrase_heavy: fused p50 {p50:.1f}ms > 0.5x host oracle "
            f"{host_p50:.1f}ms — the device positional path must at "
            "least halve phrase-heavy latency")
    _loss_audit_gate("phrase_heavy")
    return out


# ---------------------------------------------------------------------------
# unbatched traffic: serial vs coalesced vs pipelined msearch dispatch
# ---------------------------------------------------------------------------


DISPATCH_DOCS = int(os.environ.get("BENCH_DISPATCH_DOCS", 12_000))
DISPATCH_N = int(os.environ.get("BENCH_DISPATCH_N", 8))


def _strip_timing(resp: dict) -> str:
    return json.dumps({k: v for k, v in resp.items()
                       if k not in ("took", "status")},
                      sort_keys=True, default=str)


def bench_unbatched_traffic(tunnel_ms: float) -> dict:
    """The single-query latency gap scenario: N concurrent single-query
    msearch items vs the serial per-request loop. Coalesced = N
    identical-shape queries (ONE batched dispatch through the scheduler);
    pipelined = N heterogeneous shapes (back-to-back async dispatches,
    overlapped round trips). Identity-gated: the msearch items must be
    byte-identical (minus took/status) to the serial responses. Records
    the nodes_stats()["dispatch"] counters alongside."""
    from elasticsearch_tpu.node import Node

    N = DISPATCH_N
    t0 = time.time()
    docs = make_corpus(DISPATCH_DOCS)
    node = Node({"index.number_of_shards": 1})
    node.create_index("http_logs", mappings={"properties": {
        "message": {"type": "text"},
        "size": {"type": "long"},
        "status": {"type": "keyword"}}})
    for did, d in docs:
        node.index_doc("http_logs", did, d)
    node.refresh("http_logs")
    log(f"unbatched_traffic: {DISPATCH_DOCS} docs ingested in "
        f"{time.time()-t0:.1f}s")

    rng = random.Random(29)
    head = _vocab()[: 400]
    # identical-shape items: one single-term match each -> same plan
    # signature, ONE batched device dispatch for all N
    co_items = [("http_logs",
                 {"query": {"match": {"message": rng.choice(head)}},
                  "size": TOP_K}) for _ in range(N)]
    # heterogeneous shapes: i+1 should-terms -> N distinct plans, no
    # coalescing possible; the scheduler must PIPELINE their dispatches
    pipe_items = [("http_logs",
                   {"query": {"bool": {"should": [
                       {"match": {"message": rng.choice(head)}}
                       for _ in range(i + 1)],
                       "minimum_should_match": 1}},
                    "size": TOP_K}) for i in range(N)]

    def serial(items):
        return [node.search(i, dict(b)) for i, b in items]

    def batched(items):
        return node.msearch([(i, dict(b)) for i, b in items])["responses"]

    def p50_of(fn, items, reps):
        lat = []
        for _ in range(reps):
            t = time.time()
            fn(items)
            lat.append((time.time() - t) * 1000.0)
        return float(np.percentile(np.asarray(lat), 50))

    reps = max(AGG_REPS // 3, 5)
    out = {"metric": "unbatched_traffic_msearch_p50_ms", "unit": "ms",
           "n_queries": N, "docs": DISPATCH_DOCS}
    for label, items in (("coalesced", co_items), ("pipelined",
                                                   pipe_items)):
        # identity gate FIRST (doubles as compile warmup for both paths)
        want = serial(items)
        got = batched(items)
        for w, g in zip(want, got):
            if _strip_timing(w) != _strip_timing(g):
                raise AssertionError(
                    f"serial/{label} msearch responses differ")
        serial_p50 = p50_of(serial, items, reps)
        msearch_p50 = p50_of(batched, items, reps)
        out[f"serial_{label}_p50_ms"] = round(serial_p50, 2)
        out[f"{label}_p50_ms"] = round(msearch_p50, 2)
        out[f"{label}_speedup"] = round(serial_p50 / msearch_p50, 2) \
            if msearch_p50 > 0 else float("inf")
        # acceptance gate: with a real per-dispatch tunnel cost, N
        # coalesced/pipelined single queries must cost <= 0.5x the
        # serial loop. On a tunnel-less local backend (CPU CI) the flat
        # overhead the scheduler amortizes is near zero, so the ratio
        # is reported but not gated.
        if tunnel_ms > 5.0 and msearch_p50 > 0.5 * serial_p50:
            raise AssertionError(
                f"{label} msearch p50 {msearch_p50:.1f}ms > 0.5x serial "
                f"{serial_p50:.1f}ms")
    out["value"] = out["coalesced_p50_ms"]
    out["vs_baseline"] = out["coalesced_speedup"]
    ds = node.nodes_stats()["nodes"][node.name]["dispatch"]
    out["dispatch"] = {"queries": ds["queries"],
                       "coalesced_queries": ds["coalesced_queries"],
                       "batches_dispatched": ds["batches_dispatched"],
                       "pipeline_depth": ds["pipeline_depth"],
                       "window_hit_rate": round(
                           ds["window"]["hit_rate"], 4)}
    node.close()
    return out


def bench_overload_mixed_tenant(tunnel_ms: float) -> dict:
    """Traffic control plane under overload (search/traffic.py): a
    quota'd bulk tenant floods msearch from background threads while an
    unconfigured interactive tenant streams lone queries.

    Gates (tunnel backends; reported-only on tunnel-less local CI):
      * interactive p99 under the flood <= 2x its unloaded p99 — the
        priority lanes + admission shed protect the interactive class;
      * the bulk tenant is THROTTLED, never errored: shed items are
        structured 429s carrying retry_after, zero 5xx, and some items
        still make real progress;
      * the hot-query leg's repeat p50 <= 0.1x the device-dispatch p50
        — a warm generation-keyed cache hit skips the device entirely.
    """
    from elasticsearch_tpu.node import Node

    t0 = time.time()
    docs = make_corpus(DISPATCH_DOCS)
    node = Node({
        "index.number_of_shards": 1,
        # the bulk tenant: token-bucket quota + the bulk drain lane
        "search.traffic.tenant.bulk.rate": 200,
        "search.traffic.tenant.bulk.burst": 50,
        "search.traffic.tenant.bulk.lane": "bulk",
    })
    try:
        return _overload_mixed_tenant_body(node, docs, t0, tunnel_ms)
    finally:
        # close in finally: an assertion gate raising must not leak the
        # node's pools/scheduler into later scenarios (PR 9's
        # bench_concurrent_index_search lesson)
        node.close()


def _overload_mixed_tenant_body(node, docs, t0, tunnel_ms: float) -> dict:
    node.create_index("http_logs", mappings={"properties": {
        "message": {"type": "text"},
        "size": {"type": "long"},
        "status": {"type": "keyword"}}},
        settings={"index": {"cache": {"query": {
            "enable": True, "include_hits": True}}}})
    for did, d in docs:
        node.index_doc("http_logs", did, d)
    node.refresh("http_logs")
    log(f"overload_mixed_tenant: {DISPATCH_DOCS} docs ingested in "
        f"{time.time()-t0:.1f}s")

    rng = random.Random(31)
    head = _vocab()[: 400]

    def lone_body():
        # query_cache=False: the interactive leg measures REAL device
        # latency under load, not cache hits (the cache leg is below)
        return {"query": {"match": {"message": rng.choice(head)}},
                "size": TOP_K, "query_cache": False}

    inter_bodies = [lone_body() for _ in range(40)]
    flood_items = [("http_logs", lone_body()) for _ in range(8)]

    def interactive_leg():
        lat = []
        for b in inter_bodies:
            t = time.time()
            node.search("http_logs", dict(b))
            lat.append((time.time() - t) * 1000.0)
        return lat

    interactive_leg()                       # compile/warm both paths
    unloaded = interactive_leg()
    unloaded_p50, unloaded_p99 = pcts(unloaded)

    # -- the storm: background bulk msearch flood + interactive stream
    stop = threading.Event()
    flood_counts = {200: 0, 429: 0, "other": 0, "retry_after_missing": 0}
    counts_mx = threading.Lock()   # += from 3 threads is not atomic

    def flood():
        while not stop.is_set():
            resp = node.msearch(
                [(i, dict(b)) for i, b in flood_items], tenant="bulk")
            with counts_mx:
                for item in resp["responses"]:
                    s = item.get("status", 200)
                    if s == 200:
                        flood_counts[200] += 1
                    elif s == 429:
                        flood_counts[429] += 1
                        if not item.get("retry_after"):
                            flood_counts["retry_after_missing"] += 1
                    else:
                        flood_counts["other"] += 1
            # minimal client pacing: a zero-sleep spin measures GIL
            # starvation of the shed path itself (thousands of py
            # exception allocations/s), not the lanes under load
            time.sleep(0.001)

    threads = [threading.Thread(target=flood) for _ in range(3)]
    for th in threads:
        th.start()
    try:
        # warmup UNDER load first: coalescing with flood batches pads
        # to larger pow2 buckets than the unloaded leg ever exercised,
        # and the one-time XLA compile for a fresh bucket would
        # otherwise land in the measured p99 as a fake starvation spike
        interactive_leg()
        loaded = interactive_leg()
    finally:
        stop.set()
        for th in threads:
            th.join()
    loaded_p50, loaded_p99 = pcts(loaded)

    if flood_counts["other"]:
        raise AssertionError(
            f"bulk flood surfaced non-429 errors: {flood_counts}")
    if flood_counts[429] == 0:
        raise AssertionError("flood never tripped admission control")
    if flood_counts["retry_after_missing"]:
        raise AssertionError(
            f"{flood_counts['retry_after_missing']} shed items lacked "
            f"retry_after")
    if flood_counts[200] == 0:
        raise AssertionError("bulk tenant was starved outright, not "
                             "throttled")
    if tunnel_ms > 5.0 and loaded_p99 > 2.0 * unloaded_p99:
        raise AssertionError(
            f"interactive p99 {loaded_p99:.1f}ms > 2x unloaded "
            f"{unloaded_p99:.1f}ms under bulk flood")

    # -- hot-query leg: the generation-keyed device-skip cache
    hot = {"query": {"match": {"message": head[0]}}, "size": TOP_K}
    distinct = [{"query": {"match": {"message": w}}, "size": TOP_K}
                for w in head[100:100 + 20]]
    miss_lat = []
    for b in distinct:                      # all first-times: device
        t = time.time()
        node.search("http_logs", dict(b))
        miss_lat.append((time.time() - t) * 1000.0)
    node.search("http_logs", dict(hot))     # prime the entry
    hit_lat = []
    for _ in range(20):                     # all repeats: cache
        t = time.time()
        node.search("http_logs", dict(hot))
        hit_lat.append((time.time() - t) * 1000.0)
    miss_p50, _ = pcts(miss_lat)
    hit_p50, _ = pcts(hit_lat)
    if tunnel_ms > 5.0 and hit_p50 > 0.1 * miss_p50:
        raise AssertionError(
            f"hot repeat p50 {hit_p50:.2f}ms > 0.1x device-dispatch "
            f"p50 {miss_p50:.2f}ms — cache hit still paid a dispatch")

    ds = node.nodes_stats()["nodes"][node.name]["dispatch"]
    traffic = ds["traffic"]
    out = {"metric": "overload_mixed_tenant_p99_ms", "unit": "ms",
           "value": round(loaded_p99, 2),
           "unloaded_p50_ms": round(unloaded_p50, 2),
           "unloaded_p99_ms": round(unloaded_p99, 2),
           "loaded_p50_ms": round(loaded_p50, 2),
           "loaded_p99_ms": round(loaded_p99, 2),
           "p99_degradation": round(loaded_p99 / unloaded_p99, 2)
           if unloaded_p99 > 0 else float("inf"),
           "vs_baseline": round(unloaded_p99 / loaded_p99, 2)
           if loaded_p99 > 0 else float("inf"),
           "bulk_admitted": flood_counts[200],
           "bulk_rejected_429": flood_counts[429],
           "bulk_5xx": flood_counts["other"],
           "hot_query_hit_p50_ms": round(hit_p50, 3),
           "device_dispatch_p50_ms": round(miss_p50, 2),
           "cache_hit_rate": round(
               traffic["query_cache"]["hit_rate"], 4),
           "lane_depth_high_water": {
               lane: s["depth_high_water"]
               for lane, s in traffic["lanes"].items()},
           "adaptive_window_ms": traffic["window"]["last_window_ms"]}
    return out


def bench_lone_query(tunnel_ms: float) -> dict:
    """The LONE-query scenario the dispatch scheduler cannot help: a
    single request with no concurrent traffic pays one full synchronous
    dispatch on the cold path. The resident query loop
    (ES_TPU_RESIDENT_LOOP, search/resident.py) serves it from a pinned
    AOT executable with a donated, async-staged param feed instead.
    Identity-gated (resident responses must be byte-identical to cold,
    minus took); on tunnel backends the resident p50 must come in at
    <= 0.6x the cold-dispatch p50. Reports the
    nodes_stats()["dispatch"]["resident"] counters alongside."""
    from elasticsearch_tpu.node import Node
    from elasticsearch_tpu.search import resident as resident_mod

    t0 = time.time()
    docs = make_corpus(DISPATCH_DOCS)
    node = Node({"index.number_of_shards": 1})
    node.create_index("http_logs", mappings={"properties": {
        "message": {"type": "text"},
        "size": {"type": "long"},
        "status": {"type": "keyword"}}})
    for did, d in docs:
        node.index_doc("http_logs", did, d)
    node.refresh("http_logs")
    log(f"lone_query: {DISPATCH_DOCS} docs ingested in "
        f"{time.time()-t0:.1f}s")

    rng = random.Random(37)
    head = _vocab()[: 400]
    bodies = [{"query": {"match": {"message": rng.choice(head)}},
               "size": TOP_K} for _ in range(16)]
    reps = max(AGG_REPS // 3, 5)

    def p50_run():
        lat = []
        for _ in range(reps):
            for b in bodies:
                t = time.time()
                node.search("http_logs", dict(b))
                lat.append((time.time() - t) * 1000.0)
        return float(np.percentile(np.asarray(lat), 50))

    had = os.environ.pop("ES_TPU_RESIDENT_LOOP", None)
    try:
        for b in bodies:                  # cold warmup (compile + tune)
            node.search("http_logs", dict(b))
        cold_resps = [node.search("http_logs", dict(b)) for b in bodies]
        cold_p50 = p50_run()

        os.environ["ES_TPU_RESIDENT_LOOP"] = "1"
        for b in bodies:                  # resident warmup (AOT compile)
            node.search("http_logs", dict(b))
        res_resps = [node.search("http_logs", dict(b)) for b in bodies]
        for c, r in zip(cold_resps, res_resps):
            if _strip_timing(c) != _strip_timing(r):
                raise AssertionError("resident/cold responses differ")
        res_p50 = p50_run()
    finally:
        if had is None:
            os.environ.pop("ES_TPU_RESIDENT_LOOP", None)
        else:
            os.environ["ES_TPU_RESIDENT_LOOP"] = had

    # acceptance gate: with a real per-dispatch tunnel cost, the pinned
    # entry + staged feed must shed at least 40% of the lone-query
    # latency. On a tunnel-less local backend (CPU CI) the flat
    # overhead being shed is near zero, so the ratio is reported only.
    if tunnel_ms > 5.0 and res_p50 > 0.6 * cold_p50:
        raise AssertionError(
            f"resident lone-query p50 {res_p50:.1f}ms > 0.6x cold "
            f"{cold_p50:.1f}ms")
    rs = node.nodes_stats()["nodes"][node.name]["dispatch"]["resident"]
    # which engine the pinned entries actually run: pallas-tuned packs
    # are now served resident instead of falling back to cold dispatch,
    # and the loss audit must stay clean on the shapes this workload
    # tuned
    engines = {}
    for e in rs["entries"]:
        engines[e["backend"]] = engines.get(e["backend"], 0) + 1
    _loss_audit_gate("lone_query")
    node.close()
    return {"metric": "lone_query_p50_ms", "unit": "ms",
            "value": round(res_p50, 2),
            "cold_p50_ms": round(cold_p50, 2),
            "vs_baseline": round(res_p50 / cold_p50, 2)
            if cold_p50 > 0 else 1.0,
            "resident": {
                "resident_hits": rs["resident_hits"],
                "cold_dispatches": rs["cold_dispatches"],
                "evictions": rs["evictions"],
                "preempted_by_deadline": rs["preempted_by_deadline"],
                "staged_feed_overlap_ms":
                    rs["staged_feed_overlap_ms"]["high_water"],
                "entry_count": rs["entry_count"],
                "entry_engines": engines,
                "residency_bytes": rs["residency_bytes"]},
            "docs": DISPATCH_DOCS}


def bench_concurrent_index_search(tunnel_ms: float) -> dict:
    """Sustained writes + searches — the production shape the streaming
    write path (ROADMAP item 1, index.streaming.delta) exists for: a
    writer thread indexes + refreshes continuously while the read path
    serves a fused query mix. Before the delta pack, every refresh
    minted a fresh fingerprint and cold-started autotune choices,
    resident executables, and compiled programs; with it a refresh is
    an epoch bump, so the concurrent search p50 is gated at <= 1.5x the
    read-only p50 on tunnel backends. Identity-gated against a
    FULL-REBUILD ORACLE (the same final doc set indexed into a fresh
    engine and refreshed once — base + one delta, which is exactly what
    the generation pack converges to). Reports the refresh_reuses /
    compaction_evictions counters; gated so the storm never mints a
    fresh base fingerprint (a new NON-pack autotune key without a
    compaction) and a same-bucket epoch bump re-tunes ZERO keys —
    first-tune-per-delta-bucket pack keys are the documented, counted
    exception."""
    import threading
    from elasticsearch_tpu.node import Node
    from elasticsearch_tpu.search import executor as executor_mod

    t0 = time.time()
    n_docs = DISPATCH_DOCS
    docs = make_corpus(n_docs)
    mappings = {"properties": {
        "message": {"type": "text"},
        "size": {"type": "long"},
        "status": {"type": "keyword"}}}
    had = os.environ.get("ES_TPU_RESIDENT_LOOP")
    os.environ["ES_TPU_RESIDENT_LOOP"] = "1"
    node = Node({"index.number_of_shards": 1})
    try:
        node.create_index(
            "stream", settings={"index.streaming.delta": True,
                                # threshold compaction stays off for
                                # the storm: impacts are EAGER per
                                # segment, so a mid-storm fold changes
                                # which field stats scored the writer
                                # docs and no single-delta oracle can
                                # reproduce it (compaction byte-
                                # identity has its own gate in
                                # tests/test_streaming_writes.py);
                                # this scenario measures the refresh
                                # storm, where the oracle is exact
                                "index.delta.min_compact_docs": 1 << 30},
            mappings=mappings)
        for did, d in docs:
            node.index_doc("stream", did, d)
        node.refresh("stream")
        node.indices["stream"].shard(0).compact()  # seed a real base
        log(f"concurrent_index_search: {n_docs} docs ingested in "
            f"{time.time()-t0:.1f}s")

        rng = random.Random(53)
        head = _vocab()[: 400]
        bodies = [{"query": {"match": {"message": rng.choice(head)}},
                   "size": TOP_K} for _ in range(16)]
        reps = max(AGG_REPS // 3, 5)

        def p50_run():
            lat = []
            for _ in range(reps):
                for b in bodies:
                    t = time.time()
                    node.search("stream", dict(b))
                    lat.append((time.time() - t) * 1000.0)
            return float(np.percentile(np.asarray(lat), 50))

        for b in bodies:                 # warm: tune + pin residents
            node.search("stream", dict(b))
        read_only_p50 = p50_run()
        keys_before = set(executor_mod._autotune_choices)

        # -- writer storm: index + refresh while the searches run -----
        stop = threading.Event()
        written: list[int] = [0]
        writer_errors: list[BaseException] = []
        vocab = _vocab()

        def writer():
            try:
                i = 0
                wrng = random.Random(7)
                last_refresh = time.time()
                while not stop.is_set():
                    did = f"w{i}"
                    node.index_doc("stream", did, {
                        "message": " ".join(wrng.choice(vocab)
                                            for _ in range(8)),
                        "size": wrng.randint(10, 50_000),
                        "status": wrng.choice(["200", "404", "500"])})
                    i += 1
                    # ES-shaped refresh cadence (index.refresh_interval
                    # is time-based, default 1s; 200ms keeps several
                    # epoch bumps inside the measurement window)
                    if time.time() - last_refresh >= 0.2:
                        node.refresh("stream")
                        last_refresh = time.time()
                    written[0] = i
            except BaseException as e:  # noqa: BLE001 — a dead writer
                writer_errors.append(e)  # must fail the gate, not
                                         # silently idle the storm
        wt = threading.Thread(target=writer, daemon=True)
        wt.start()
        try:
            concurrent_p50 = p50_run()
        finally:
            stop.set()
            wt.join(timeout=10.0)
        if writer_errors:
            raise AssertionError(
                "concurrent_index_search: the writer storm died: "
                f"{writer_errors[0]!r}")
        if written[0] == 0:
            raise AssertionError(
                "concurrent_index_search: writer made no progress — "
                "the gates would be vacuous")
        node.refresh("stream")
        new_keys = set(executor_mod._autotune_choices) - keys_before
        rs = node.nodes_stats()["nodes"][node.name]["dispatch"]["resident"]
        streaming = node.indices["stream"].shard(0).segment_stats().get(
            "streaming", {})

        # identity gate vs the full-rebuild oracle: the SAME final doc
        # set in a fresh delta-mode engine, one refresh (base + one
        # delta — the state the generation pack converges to)
        final_resps = [node.search("stream", dict(b)) for b in bodies]
        oracle = Node({"index.number_of_shards": 1})
        try:
            oracle.create_index(
                "stream", settings={"index.streaming.delta": True,
                                    "index.delta.min_compact_docs": 1 << 30},
                mappings=mappings)
            for did, d in docs:
                oracle.index_doc("stream", did, d)
            oracle.refresh("stream")
            oracle.indices["stream"].shard(0).compact()
            eng = node.indices["stream"].shard(0)
            for did, _ver, src in eng.snapshot_docs():
                # writer docs in their original visibility order
                # (snapshot order preserves it through any mid-storm
                # compaction)
                if did.startswith("w"):
                    oracle.index_doc("stream", did, src)
            oracle.refresh("stream")
            oracle_resps = [oracle.search("stream", dict(b)) for b in bodies]
            for a, b in zip(final_resps, oracle_resps):
                if _strip_timing(a) != _strip_timing(b):
                    raise AssertionError(
                        "concurrent_index_search: delta-pack response "
                        "diverged from the full-rebuild oracle")
        finally:
            oracle.close()

        # the refresh storm must not re-key the surviving generation.
        # The FIRST search over a never-before-seen (base, delta
        # bucket) pack necessarily tunes that pack key once — and again
        # when the growing delta crosses a pow2 capacity bucket; both
        # are the documented re-key events, not regressions. What a
        # refresh must NEVER do is mint a fresh base fingerprint: that
        # shows up here as a new NON-pack autotune key (single-segment
        # keys are fingerprint-tuples, pack keys start with "pack") —
        # and with threshold compaction disabled for the storm, there
        # is no legitimate source of one.
        base_rekeys = [k for k in new_keys
                       if not (isinstance(k, tuple) and k
                               and k[0] == "pack")]
        if base_rekeys:
            raise AssertionError(
                f"refresh storm re-tuned {len(base_rekeys)} non-pack "
                f"autotune keys (generation keying regressed): "
                f"{sorted(map(repr, base_rekeys))[:3]}")
        # direct acceptance check: an epoch bump whose delta stays in
        # its pow2 bucket performs ZERO autotune re-tunes
        eng = node.indices["stream"].shard(0)
        d0 = eng._delta_seg
        if d0 is not None and d0.num_docs + 4 < d0.capacity:
            cap0, tunes_mid = d0.capacity, len(executor_mod._autotune_choices)
            for j in range(3):
                node.index_doc("stream", f"zb{j}", {
                    "message": "epoch bump probe", "size": 1,
                    "status": "200"})
            node.refresh("stream")
            d1 = eng._delta_seg
            if d1 is not None and d1.capacity == cap0:
                for b in bodies:
                    node.search("stream", dict(b))
                bump_tunes = (len(executor_mod._autotune_choices)
                              - tunes_mid)
                if bump_tunes:
                    raise AssertionError(
                        f"a same-bucket epoch bump re-tuned "
                        f"{bump_tunes} autotune keys (generation "
                        "keying regressed)")
        if tunnel_ms > 5.0 and concurrent_p50 > 1.5 * read_only_p50:
            raise AssertionError(
                f"concurrent search p50 {concurrent_p50:.1f}ms > 1.5x "
                f"read-only {read_only_p50:.1f}ms")
    finally:
        if had is None:
            os.environ.pop("ES_TPU_RESIDENT_LOOP", None)
        else:
            os.environ["ES_TPU_RESIDENT_LOOP"] = had
        node.close()
    return {"metric": "concurrent_index_search_p50_ms", "unit": "ms",
            "value": round(concurrent_p50, 2),
            "read_only_p50_ms": round(read_only_p50, 2),
            "vs_baseline": (round(concurrent_p50 / read_only_p50, 2)
                            if read_only_p50 > 0 else 1.0),
            "docs_written_during_run": written[0],
            "new_pack_bucket_tunes": len(new_keys) - len(base_rekeys),
            "base_rekeys_during_storm": len(base_rekeys),
            "resident": {
                "refresh_reuses": rs["refresh_reuses"],
                "compaction_evictions": rs["compaction_evictions"],
                "evictions": rs["evictions"],
                "resident_hits": rs["resident_hits"],
                "cold_dispatches": rs["cold_dispatches"]},
            "streaming": streaming}


def bench_crash_recovery() -> dict:
    """Recovery wall time after a write storm (ISSUE 15): ingest the
    dispatch-scale corpus into a path-backed node (periodic flushes +
    an unflushed translog tail — the abrupt-shutdown shape Engine.close
    leaves, since close never flushes), then time a cold reopen:
    commit load + translog replay + searcher publication. The CLEAN
    path is gated: zero corruptions detected, zero commit fallbacks,
    zero truncated translog bytes, zero contained shards — recovery
    salvage machinery must be provably idle when nothing is wrong."""
    import shutil
    import tempfile
    from elasticsearch_tpu.node import Node

    n_docs = DISPATCH_DOCS
    docs = make_corpus(n_docs)
    data_path = tempfile.mkdtemp(prefix="bench_crash_recovery_")
    mappings = {"properties": {
        "message": {"type": "text"},
        "size": {"type": "long"},
        "status": {"type": "keyword"}}}
    t0 = time.time()
    node = Node({"path.data": data_path, "node.name": "crash-bench",
                 "index.number_of_shards": 1})
    node2 = None
    try:
        # async durability for the storm half: the leg measures
        # RECOVERY, and per-op fsync would make ingest dominate the
        # wall clock without changing what recovery replays (the ops
        # are flushed to the file either way; fsync cadence only
        # matters under power loss, which tests/test_durability.py
        # covers deterministically)
        node.create_index("wal", mappings=mappings, settings={
            "index.translog.durability": "async"})
        flush_every = max(n_docs // 4, 1)
        for i, (did, d) in enumerate(docs):
            node.index_doc("wal", did, d)
            if (i + 1) % flush_every == 0 and (i + 1) < n_docs:
                node.flush("wal")
        # the last ~quarter stays translog-only: recovery must replay
        node.close()
        log(f"crash_recovery: {n_docs} docs ingested in "
            f"{time.time() - t0:.1f}s; reopening")
        t1 = time.time()
        node2 = Node({"path.data": data_path,
                      "node.name": "crash-bench"})
        node2.refresh("wal")
        recovery_ms = (time.time() - t1) * 1000.0
        r = node2.search("wal", {"query": {"match_all": {}},
                                 "size": 0})
        if r["hits"]["total"] != n_docs:
            raise AssertionError(
                f"crash_recovery: {r['hits']['total']} of {n_docs} "
                "docs survived a clean-shutdown recovery")
        dur = node2.nodes_stats()["nodes"]["crash-bench"][
            "indices"]["durability"]
        for key in ("corruptions_detected", "commits_fell_back",
                    "translog_truncated_bytes", "segments_salvaged",
                    "shards_failed_corrupt"):
            if dur[key] != 0:
                raise AssertionError(
                    f"crash_recovery: salvage counter [{key}]="
                    f"{dur[key]} on the CLEAN path (expected 0)")
        if not node2.verify_integrity()["clean"]:
            raise AssertionError(
                "crash_recovery: store verify unclean after recovery")
        return {"metric": "crash_recovery_ms",
                "value": round(recovery_ms, 1), "unit": "ms",
                "vs_baseline": 1.0,
                "docs": n_docs,
                "docs_per_s_recovered": round(
                    n_docs / (recovery_ms / 1000.0), 1),
                "durability_counters": dur,
                "note": "cold reopen after a write storm: commit load "
                        "+ translog replay + refresh; salvage "
                        "counters gated to zero on the clean path"}
    finally:
        if node2 is not None:
            node2.close()
        shutil.rmtree(data_path, ignore_errors=True)


def bench_oversubscribed_corpus(tunnel_ms: float) -> dict:
    """Beyond-HBM packs (index/tiering.py): the SAME corpus served
    fully resident vs through tiered tile residency with the HBM
    budget shrunk (via ES_TPU_TIERED_BUDGET_BYTES) until the pack is
    ~6x the budget — a CI-sized stand-in for a corpus that genuinely
    cannot fit the device. The workload is the HIGH-PRUNE-RATE shape
    tiering exists for: selective head terms whose postings live in a
    few tiles, so the bound computation over the resident summaries
    filters most fetches (prune_skipped_fetches must come out nonzero
    — proving pruning filters I/O, not just FLOPs). Gates: responses
    byte-identical to the fully-resident run, and on tunnel backends
    the tiered p50 must hold at <= 2x fully resident. Reports the
    tiering counters (hits/misses/evictions/prune-skipped/overlap)."""
    from elasticsearch_tpu.node import Node
    from elasticsearch_tpu.index import tiering as tiering_mod

    def build_node():
        node = Node({"index.number_of_shards": 1})
        node.create_index("logs", mappings={"properties": {
            "message": {"type": "text"},
            "size": {"type": "long"},
            "status": {"type": "keyword"}}})
        for did, d in docs:
            node.index_doc("logs", did, d)
        node.refresh("logs")
        return node

    t0 = time.time()
    docs = make_corpus(DISPATCH_DOCS)
    rng = random.Random(71)
    head = _vocab()[: 400]
    bodies = [{"query": {"match": {"message": rng.choice(head)}},
               "size": TOP_K} for _ in range(16)]
    reps = max(AGG_REPS // 3, 5)

    def p50_run(node):
        lat = []
        for _ in range(reps):
            for b in bodies:
                t = time.time()
                node.search("logs", dict(b))
                lat.append((time.time() - t) * 1000.0)
        return float(np.percentile(np.asarray(lat), 50))

    had = {k: os.environ.pop(k, None)
           for k in ("ES_TPU_TIERED_PACK", "ES_TPU_TIERED_BUDGET_BYTES")}
    node = tiered_node = None
    try:
        # -- fully resident reference ---------------------------------
        node = build_node()
        log(f"oversubscribed_corpus: {DISPATCH_DOCS} docs ingested in "
            f"{time.time()-t0:.1f}s")
        for b in bodies:                  # compile + tune warmup
            node.search("logs", dict(b))
        resident_resps = [node.search("logs", dict(b)) for b in bodies]
        resident_p50 = p50_run(node)
        # size the budget off the REAL pack: forward index + columns
        seg = node.indices["logs"].shard(0).segments[0]
        fwd_bytes = sum(pf.fwd_tids.nbytes + pf.fwd_imps.nbytes
                        for pf in seg.text.values()
                        if pf.fwd_tids is not None)
        pack_bytes = seg.nbytes() + fwd_bytes
        node.close()
        node = None

        # -- tiered run: corpus ~6x the budget ------------------------
        tiering_mod.reset()
        os.environ["ES_TPU_TIERED_PACK"] = "1"
        os.environ["ES_TPU_TIERED_BUDGET_BYTES"] = str(
            max(pack_bytes // 6, 1))
        tiered_node = build_node()
        for b in bodies:                  # compile warmup (chunk progs)
            tiered_node.search("logs", dict(b))
        tiered_resps = [tiered_node.search("logs", dict(b))
                        for b in bodies]
        for r_ref, r_t in zip(resident_resps, tiered_resps):
            if _strip_timing(r_ref) != _strip_timing(r_t):
                raise AssertionError(
                    "tiered/fully-resident responses differ")
        tiered_p50 = p50_run(tiered_node)
        snap = tiering_mod.stats_snapshot()
        if snap["tiered_dispatches"] == 0:
            raise AssertionError(
                "oversubscribed corpus never took the tiered path — "
                "the gate would be vacuous")
        if snap["prune_skipped_fetches"] == 0:
            raise AssertionError(
                "no prune-skipped fetches: pruning filtered zero I/O "
                "on a high-prune-rate workload")
        if tunnel_ms > 5.0 and tiered_p50 > 2.0 * resident_p50:
            raise AssertionError(
                f"tiered p50 {tiered_p50:.1f}ms exceeds 2x fully-"
                f"resident {resident_p50:.1f}ms")
    finally:
        for n in (node, tiered_node):
            if n is not None:
                n.close()
        for k, v in had.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        tiering_mod.reset()
    return {"metric": "oversubscribed_corpus_p50_ms",
            "value": round(tiered_p50, 2), "unit": "ms",
            "vs_baseline": round(tiered_p50 / resident_p50, 2)
            if resident_p50 > 0 else 1.0,
            "fully_resident_p50_ms": round(resident_p50, 2),
            "pack_bytes": int(pack_bytes),
            "budget_bytes": int(max(pack_bytes // 6, 1)),
            "oversubscription": 6.0,
            "tiering": {k: snap[k] for k in (
                "tile_hits", "tile_misses", "tile_evictions",
                "prune_skipped_fetches", "tiered_dispatches",
                "resident_bytes", "summary_bytes",
                "prefetch_overlap_ms")},
            "docs": DISPATCH_DOCS}


def bench_degraded_search(tunnel_ms: float) -> dict:
    """Partial-failure scenario: p50 + result-completeness of a
    multi-shard search with one injected dead shard and one injected
    slow shard (utils/faults.py), vs the healthy baseline. Gates that a
    DEAD shard degrades gracefully — the search must not retry-loop or
    stall, so its p50 may exceed healthy by at most one failover round
    trip (tunnel_ms) plus noise margin. The slow-shard leg reports the
    deadline path (`timed_out: true`, laggard failed) un-gated."""
    from elasticsearch_tpu.node import Node
    from elasticsearch_tpu.utils import faults

    t0 = time.time()
    docs = make_corpus(DISPATCH_DOCS)
    node = Node({"index.number_of_shards": 3})
    node.create_index("http_logs", mappings={"properties": {
        "message": {"type": "text"},
        "size": {"type": "long"},
        "status": {"type": "keyword"}}})
    for did, d in docs:
        node.index_doc("http_logs", did, d)
    node.refresh("http_logs")
    log(f"degraded_search: {DISPATCH_DOCS} docs / 3 shards ingested in "
        f"{time.time()-t0:.1f}s")

    rng = random.Random(31)
    head = _vocab()[: 400]
    bodies = [{"query": {"match": {"message": rng.choice(head)}},
               "size": TOP_K} for _ in range(40)]
    reps = max(AGG_REPS // 3, 5)

    def p50_run():
        lat = []
        for _ in range(reps):
            t = time.time()
            for b in bodies:
                node.search("http_logs", dict(b))
            lat.append((time.time() - t) * 1000.0 / len(bodies))
        return float(np.percentile(np.asarray(lat), 50))

    for b in bodies:                      # compile warmup
        node.search("http_logs", dict(b))
    healthy_p50 = p50_run()
    healthy_total = sum(node.search("http_logs", dict(b))["hits"]["total"]
                        for b in bodies)

    try:
        faults.configure("shard_error:shard=1:index=http_logs")
        dead_p50 = p50_run()
        dead_resps = [node.search("http_logs", dict(b)) for b in bodies]
    finally:
        faults.clear()
    assert all(r["_shards"]["failed"] == 1 for r in dead_resps)
    dead_total = sum(r["hits"]["total"] for r in dead_resps)
    completeness = dead_total / healthy_total if healthy_total else 1.0

    # slow-shard leg: straggler + deadline -> timed_out partials
    try:
        faults.configure("shard_delay:ms=50:shard=2:index=http_logs")
        slow = [node.search("http_logs", dict(b, timeout="20ms"))
                for b in bodies[:10]]
    finally:
        faults.clear()
    timed_out_frac = sum(r["timed_out"] for r in slow) / len(slow)

    # acceptance gate: one dead shard may add at most one failover
    # round trip (the isolation retry re-dispatches the failed job
    # once) on top of healthy p50, plus a noise margin
    limit = healthy_p50 + tunnel_ms + max(0.5 * healthy_p50, 10.0)
    if dead_p50 > limit:
        raise AssertionError(
            f"degraded p50 {dead_p50:.1f}ms exceeds healthy "
            f"{healthy_p50:.1f}ms + one round trip ({limit:.1f}ms)")

    ds = node.nodes_stats()["nodes"][node.name]["dispatch"]
    eviction = bench_eviction_leg(tunnel_ms)
    node.close()
    return {"metric": "degraded_search_p50_ms",
            "value": round(dead_p50, 2), "unit": "ms",
            "vs_baseline": round(dead_p50 / healthy_p50, 2)
            if healthy_p50 > 0 else 1.0,
            "healthy_p50_ms": round(healthy_p50, 2),
            "completeness": round(completeness, 4),
            "timed_out_frac": round(timed_out_frac, 2),
            "failover": ds["failover"],
            "eviction": eviction, "docs": DISPATCH_DOCS}


def bench_eviction_leg(tunnel_ms: float) -> dict:
    """Elastic-mesh leg of the degraded scenario: one replica row
    PERMANENTLY dead (`device_dead` injection). Before eviction every
    search pays a failover round trip; the health tracker evicts the
    row, a background repack re-shards onto the survivors while the old
    pack keeps serving, and the searcher swap removes the tax. Gates
    (tunnel backends): after eviction settles, p50 must return to
    within 1.1x the healthy mesh p50; results are byte-identical to
    healthy across the WHOLE lifecycle (dying, during-repack, settled,
    re-expanded); re-expansion restores full replication; counters
    prove each stage ran."""
    import jax
    if len(jax.devices()) < 4:
        return {"skipped": f"needs >= 4 devices for a 2x2 mesh, "
                           f"have {len(jax.devices())}"}
    from elasticsearch_tpu.node import Node
    from elasticsearch_tpu.parallel.mesh import build_mesh
    from elasticsearch_tpu.parallel.repack import ElasticMeshSearcher
    from elasticsearch_tpu.utils import faults

    docs = make_corpus(DISPATCH_DOCS)
    node = Node({"node.name": "bench-evict"})
    node.create_index("ev_logs",
                      settings={"index.number_of_shards": 2},
                      mappings={"properties": {
                          "message": {"type": "text"},
                          "size": {"type": "long"},
                          "status": {"type": "keyword"}}})
    for did, d in docs:
        node.index_doc("ev_logs", did, d)
    node.refresh("ev_logs")

    rng = random.Random(37)
    head = _vocab()[: 400]
    bodies = [{"query": {"match": {"message": rng.choice(head)}},
               "size": TOP_K} for _ in range(20)]
    reps = max(AGG_REPS // 5, 4)

    es = ElasticMeshSearcher(node, "ev_logs", build_mesh(2, 2),
                             failure_threshold=3, probe_interval_ms=50)

    def strip(r):
        return json.dumps({k: v for k, v in r.items() if k != "took"},
                          sort_keys=True, default=str)

    def p50_run():
        lat = []
        for _ in range(reps):
            t = time.time()
            for b in bodies:
                es.search(dict(b))
            lat.append((time.time() - t) * 1000.0 / len(bodies))
        return float(np.percentile(np.asarray(lat), 50))

    for b in bodies:                      # compile warmup
        es.search(dict(b))
    healthy = [strip(es.search(dict(b))) for b in bodies]
    healthy_p50 = p50_run()

    from elasticsearch_tpu.search import dispatch as _dm
    try:
        return _run_eviction_leg(es, node, bodies, healthy, healthy_p50,
                                 strip, p50_run, tunnel_ms, _dm)
    finally:
        # gates may raise mid-lifecycle: the searcher's breaker hold
        # and the node must never leak into the rest of the bench run
        faults.clear()
        es.close()
        node.close()


def _run_eviction_leg(es, node, bodies, healthy, healthy_p50, strip,
                      p50_run, tunnel_ms, _dm) -> dict:
    from elasticsearch_tpu.utils import faults
    try:
        faults.configure("device_dead:replica=0:site=mesh")
        # dying phase: every search succeeds (failover tax) until the
        # threshold evicts; then searches keep succeeding DURING the
        # background repack — identity asserted throughout, the loop
        # only stops once the swap lands (n_replicas drops to 1)
        during = 0
        rounds = 0
        while es.n_replicas == 2 and rounds < 200:
            for b, w in zip(bodies, healthy):
                if strip(es.search(dict(b))) != w:
                    raise AssertionError(
                        "response diverged during eviction/repack")
                during += 1
            rounds += 1
        if not es.await_settled(60.0):
            raise AssertionError("eviction did not settle")
        if es.n_replicas != 1:
            raise AssertionError("dead row was not evicted")
        for b, w in zip(bodies, healthy):      # post-swap warmup + identity
            if strip(es.search(dict(b))) != w:
                raise AssertionError("response diverged across the swap")
        retries_before = _dm.failover_stats.retries.count
        settled_p50 = p50_run()
        tax_retries = _dm.failover_stats.retries.count - retries_before
    finally:
        faults.clear()

    # no per-search failover tax after the swap
    if tax_retries != 0:
        raise AssertionError(
            f"{tax_retries} failover retries after eviction settled")
    # latency gate on tunnel backends (flat round trips dominate there);
    # reported-only on tunnel-less local CI where noise swamps the ratio
    if tunnel_ms > 5.0 and settled_p50 > 1.1 * healthy_p50:
        raise AssertionError(
            f"settled degraded p50 {settled_p50:.1f}ms > 1.1x healthy "
            f"mesh p50 {healthy_p50:.1f}ms")

    # re-expansion: the injected death is lifted -> probe -> full mesh
    es.probe_now()
    if not es.await_settled(60.0):
        raise AssertionError("re-expansion did not settle")
    if es.n_replicas != 2:
        raise AssertionError("re-expansion did not restore replication")
    for b, w in zip(bodies, healthy):
        if strip(es.search(dict(b))) != w:
            raise AssertionError("response diverged after re-expansion")

    ev = _dm.eviction_stats.snapshot()
    if not (ev["rows_dead"] >= 1 and ev["repacks"] >= 2
            and ev["swaps"] >= 2 and ev["re_expansions"] >= 1):
        raise AssertionError(f"lifecycle counters incomplete: {ev}")
    log(f"eviction: healthy {healthy_p50:.2f}ms settled "
        f"{settled_p50:.2f}ms during-repack searches {during}")
    return {"healthy_mesh_p50_ms": round(healthy_p50, 2),
            "settled_p50_ms": round(settled_p50, 2),
            "vs_healthy": round(settled_p50 / healthy_p50, 2)
            if healthy_p50 > 0 else 1.0,
            "searches_during_lifecycle": during,
            "counters": ev}


# ---------------------------------------------------------------------------
# nyc_taxis corpus for configs [2] and [3]
# ---------------------------------------------------------------------------


TAXI_BASE = 1420070400  # 2015-01-01, the nyc_taxis epoch


def build_taxis():
    """20M-row columnar load (build_columnar: the bulk ingestion path —
    a doc-by-doc parse would take ~10 minutes at this scale)."""
    t0 = time.time()
    from elasticsearch_tpu.index.mapping import MapperService
    from elasticsearch_tpu.index.segment import build_columnar
    rng = np.random.default_rng(5)
    zones = rng.integers(0, TAXI_CARD, size=TAXI_ROWS).astype(np.int32)
    ts = (TAXI_BASE + rng.integers(0, 365 * 86400, size=TAXI_ROWS))
    fare = np.round(rng.gamma(2.5, 6.0, size=TAXI_ROWS), 2)
    terms = [f"z{i:05d}" for i in range(TAXI_CARD)]
    seg = build_columnar(
        "taxis", TAXI_ROWS,
        keywords={"zone": (terms, zones)},
        numerics={"ts": ("date", ts.astype(np.int64) * 1000),
                  "fare": ("double", fare)})
    svc = MapperService(mapping={"properties": {
        "zone": {"type": "keyword"},
        "ts": {"type": "date"},
        "fare": {"type": "double"}}})
    live = np.zeros(seg.capacity, dtype=bool)
    live[:TAXI_ROWS] = True
    log(f"nyc_taxis: {TAXI_ROWS} rows, zone card={TAXI_CARD}, "
        f"built in {time.time()-t0:.1f}s")
    return svc, seg, live, zones, ts, fare


def _reader(svc, seg, live):
    from elasticsearch_tpu.search.shard_searcher import ShardReader
    return ShardReader("taxis", [seg], {seg.seg_id: live}, svc)


def taxi_windows(n: int, seed: int = 17) -> list[tuple[int, int]]:
    """Randomized 30-65 day pickup-time windows (the Rally autohisto/
    date-range pattern): every query in a batch scans the corpus under a
    DIFFERENT filter, so no caching/dedup can stand in for the scan."""
    rng = random.Random(seed)
    out = []
    for _ in range(n):
        lo = TAXI_BASE + rng.randrange(0, 300 * 86400)
        hi = lo + rng.randrange(30, 65) * 86400
        out.append((lo, hi))
    return out


def measure_tunnel_ms() -> float:
    """Flat per-dispatch round trip of the axon dev tunnel: the p50 of a
    trivial jitted program + device_get. This is serving-stack overhead,
    not compute — reported separately so device compute is legible."""
    import jax
    import jax.numpy as jnp
    f = jax.jit(lambda x: x + 1.0)
    x = jnp.zeros(8, jnp.float32)
    jax.device_get(f(x))
    lat = []
    for _ in range(15):
        t0 = time.time()
        jax.device_get(f(x))
        lat.append((time.time() - t0) * 1000.0)
    return float(np.percentile(lat, 50))


def _agg_lat(reader, body_fn, windows, batch: int
             ) -> tuple[float, float, float]:
    """(single p50, single p99, batched per-query ms) over VARYING
    windows. The batched figure divides one B-wide msearch (ONE device
    program — the deployment shape) by B; the single-query p50 carries
    the per-dispatch tunnel round-trip (~65ms) on top of the compute."""
    reader.search(body_fn(*windows[0]))  # compile single
    lat = []
    for i in range(AGG_REPS):
        w = windows[i % len(windows)]
        t0 = time.time()
        reader.search(body_fn(*w))
        lat.append((time.time() - t0) * 1000.0)
    p50, p99 = pcts(lat)
    bodies = [body_fn(*w) for w in windows[:batch]]
    reader.msearch([dict(b) for b in bodies])  # compile batched program
    blat = []
    for _ in range(max(AGG_REPS // 10, 2)):
        t0 = time.time()
        reader.msearch([dict(b) for b in bodies])
        blat.append((time.time() - t0) * 1000.0 / batch)
    return p50, p99, float(np.min(blat))


def _terms_body(lo: int, hi: int) -> dict:
    return {"size": 0,
            "query": {"range": {"ts": {"gte": lo * 1000,
                                       "lt": hi * 1000}}},
            "aggs": {"zones": {"terms": {"field": "zone", "size": 10}}}}


def bench_terms_agg(reader, zones, ts, tunnel_ms: float) -> dict:
    _fused_reset()
    windows = taxi_windows(256)
    p50, p99, batched_ms = _agg_lat(reader, _terms_body, windows,
                                    batch=256)
    # correctness: exact filtered top-10 counts vs numpy on 2 windows
    for lo, hi in windows[:2]:
        r = reader.search(_terms_body(lo, hi))
        m = (ts >= lo) & (ts < hi)
        counts = np.bincount(zones[m], minlength=TAXI_CARD)
        top = np.argsort(-counts, kind="stable")[:10]
        got = {b["key"]: b["doc_count"]
               for b in r["aggregations"]["zones"]["buckets"]}
        want = {f"z{int(z):05d}": int(counts[z]) for z in top}
        if sorted(got.values()) != sorted(want.values()):
            raise AssertionError(f"terms agg mismatch: {got} vs {want}")
        if r["hits"]["total"] != int(m.sum()):
            raise AssertionError("terms agg total mismatch")

    # CPU baseline: SAME filtered scan at the SAME row count
    cpu_windows = windows[:4]

    def _cpu():
        for lo, hi in cpu_windows:
            m = (ts >= lo) & (ts < hi)
            c = np.bincount(zones[m], minlength=TAXI_CARD)
            np.argpartition(-c, 10)[:10]
    cpu_ms = best_time(_cpu) * 1000.0 / len(cpu_windows)
    return {"metric": "nyc_taxis_terms_agg_ms_per_query",
            "value": round(batched_ms, 3), "unit": "ms",
            "vs_baseline": round(cpu_ms / batched_ms, 2),
            "p50_ms": round(p50, 2), "p99_ms": round(p99, 2),
            "single_query_p50_ms": round(p50, 2),
            "single_device_p50_ms": round(max(p50 - tunnel_ms, 0.0), 2),
            "batch": 256, "cpu_ms": round(cpu_ms, 3),
            "rows": TAXI_ROWS,
            "query": "randomized 30-65d ts range filter",
            "fused": _fused_block()}


def _hist_body(lo: int, hi: int) -> dict:
    return {"size": 0,
            "query": {"range": {"ts": {"gte": lo * 1000,
                                       "lt": hi * 1000}}},
            "aggs": {"per_week": {
                "date_histogram": {"field": "ts", "interval": "week"},
                "aggs": {"avg_fare": {"avg": {"field": "fare"}},
                         "total": {"sum": {"field": "fare"}}}}}}


def bench_date_histogram(reader, ts, fare, tunnel_ms: float) -> dict:
    _fused_reset()
    windows = taxi_windows(256, seed=23)
    p50, p99, batched_ms = _agg_lat(reader, _hist_body, windows,
                                    batch=256)
    # correctness: exact per-bucket counts + sum tolerance on 2 windows
    week = 7 * 86400
    for lo, hi in windows[:2]:
        r = reader.search(_hist_body(lo, hi))
        m = (ts >= lo) & (ts < hi)
        origin = (ts.min() // week) * week
        wk = (ts[m] - origin) // week
        counts = np.bincount(wk)
        nz = np.nonzero(counts)[0]
        got = {b["key"]: b["doc_count"]
               for b in r["aggregations"]["per_week"]["buckets"]
               if b["doc_count"]}
        want = {int(origin + w * week) * 1000: int(counts[w]) for w in nz}
        if got != want:
            raise AssertionError(
                f"date_histogram counts mismatch ({len(got)} vs "
                f"{len(want)} buckets)")
        total_got = sum(b["total"]["value"]
                        for b in r["aggregations"]["per_week"]["buckets"])
        if not np.isclose(total_got, float(fare[m].sum()), rtol=1e-3):
            raise AssertionError(
                f"date_histogram sum mismatch: {total_got} "
                f"vs {fare[m].sum()}")

    cpu_windows = windows[:4]

    def _cpu():
        for lo, hi in cpu_windows:
            m = (ts >= lo) & (ts < hi)
            wk = (ts[m] - TAXI_BASE) // week
            counts = np.bincount(wk, minlength=54)
            sums = np.bincount(wk, weights=fare[m], minlength=54)
            sums / np.maximum(counts, 1)
    cpu_ms = best_time(_cpu) * 1000.0 / len(cpu_windows)
    return {"metric": "nyc_taxis_date_histogram_ms_per_query",
            "value": round(batched_ms, 3), "unit": "ms",
            "vs_baseline": round(cpu_ms / batched_ms, 2),
            "p50_ms": round(p50, 2), "p99_ms": round(p99, 2),
            "single_query_p50_ms": round(p50, 2),
            "single_device_p50_ms": round(max(p50 - tunnel_ms, 0.0), 2),
            "batch": 256, "cpu_ms": round(cpu_ms, 3),
            "rows": TAXI_ROWS,
            "query": "randomized 30-65d ts range filter",
            "fused": _fused_block()}


# ---------------------------------------------------------------------------
# config[4]: dense_vector kNN + BM25 rescore
# ---------------------------------------------------------------------------


def bench_knn() -> dict:
    import functools
    import jax
    import jax.numpy as jnp
    from elasticsearch_tpu.ops.knn import knn_topk

    rng = np.random.default_rng(23)
    t0 = time.time()
    emb = rng.standard_normal((KNN_DOCS, KNN_DIM),
                              dtype=np.float32)
    bm25 = rng.gamma(2.0, 2.0, size=KNN_DOCS).astype(np.float32)
    queries = rng.standard_normal(
        (KNN_BATCH * 4, KNN_DIM)).astype(np.float32)
    norms = np.linalg.norm(emb, axis=1).astype(np.float32)
    dev_emb = jnp.asarray(emb, dtype=jnp.bfloat16)  # MXU-native storage
    dev_norms = jnp.asarray(norms)
    dev_exists = jnp.ones(KNN_DOCS, bool)
    dev_live = jnp.ones(KNN_DOCS, bool)
    dev_bm25 = jnp.asarray(bm25)
    log(f"knn: {KNN_DOCS} x {KNN_DIM} vectors in {time.time()-t0:.1f}s")

    @functools.partial(jax.jit, static_argnames=("k", "window"))
    def knn_rescore(qv, v, nrm, b25, k: int, window: int):
        # retrieve `window` candidates by cosine (approx_max_k at 0.99
        # recall — the HNSW-stage analog), rescore EXACTLY with BM25 sum
        # in the same program (the ES hybrid rule: combined = knn_score
        # + rescore query). Corpus arrays ride as arguments: a 0.5GB
        # closure constant would be baked into the uploaded HLO.
        scores, idx = knn_topk(v, nrm, dev_exists, dev_live,
                               qv, similarity="cosine", k=window,
                               approx_recall=0.99)
        combined = scores + b25[idx]
        order = jnp.argsort(-combined, axis=1)[:, :k]
        return (jnp.take_along_axis(combined, order, axis=1),
                jnp.take_along_axis(idx, order, axis=1))

    batches = [queries[i * KNN_BATCH: (i + 1) * KNN_BATCH]
               for i in range(4)]

    def run():
        return throughput_and_latency(
            batches,
            lambda b: knn_rescore(jnp.asarray(b), dev_emb, dev_norms,
                                  dev_bm25, TOP_K, 100),
            jax.block_until_ready)

    run()
    total_s, lat = run()
    qps = len(queries) / total_s
    p50, p99 = pcts(lat)

    # CPU baseline at the SAME scale: exact-window retrieve + rescore
    qn = queries[:32]

    def _cpu():
        qnorm = np.linalg.norm(qn, axis=1, keepdims=True)
        s_ = (1.0 + (qn @ emb.T) / (qnorm * norms[None, :] + 1e-9)) / 2.0
        for row in range(qn.shape[0]):
            cand = np.argpartition(-s_[row], 100)[:100]
            comb = s_[row][cand] + bm25[cand]
            cand[np.argsort(-comb)[:TOP_K]]
    cpu_qps = qn.shape[0] / best_time(_cpu)

    # matched-recall gate: measured recall@10 of the (approx retrieve +
    # exact rescore) pipeline against the exact CPU pipeline, averaged
    # over 32 queries — the methodology HNSW itself is judged by
    qnorm = np.linalg.norm(qn, axis=1, keepdims=True)
    sims = (1.0 + (qn @ emb.T) / (qnorm * norms[None, :] + 1e-9)) / 2.0
    s, i_dev = knn_rescore(jnp.asarray(qn), dev_emb, dev_norms,
                           dev_bm25, TOP_K, 100)
    i_dev = np.asarray(i_dev)
    hits = 0
    for row in range(qn.shape[0]):
        cand = np.argpartition(-sims[row], 100)[:100]
        exact_ids = cand[np.argsort(-(sims[row][cand]
                                      + bm25[cand]))][:TOP_K]
        hits += len(set(map(int, exact_ids))
                    & set(map(int, i_dev[row][:TOP_K])))
    recall = hits / (qn.shape[0] * TOP_K)
    if recall < 0.85:
        raise AssertionError(f"knn recall@10 too low: {recall:.3f}")
    return {"metric": "msmarco_knn_rescore_qps", "value": round(qps, 1),
            "unit": "qps", "vs_baseline": round(qps / cpu_qps, 2),
            "p50_ms": round(p50, 1), "p99_ms": round(p99, 1),
            "recall_at_10": round(recall, 3), "docs": KNN_DOCS,
            "dim": KNN_DIM}


def bench_knn_10m() -> dict:
    """IVF cluster-pruned ANN at 10M x 256 (ROADMAP item 1): recall@10
    >= 0.95 HARD GATE against the exact device scan, qps vs the exact
    path reported, cluster-prune counters proving the bound-vs-
    threshold skip fires. On the CPU CI backend the leg runs a scaled
    proxy (BENCH_KNN10M_DOCS/_DIM) — the gate applies at every scale;
    the 10M x 256 numbers come from the TPU run."""
    import functools
    import jax
    import jax.numpy as jnp
    from elasticsearch_tpu.index.ann import build_ann, default_nprobe
    from elasticsearch_tpu.ops.ann import ivf_topk
    from elasticsearch_tpu.ops.knn import knn_topk

    on_tpu = jax.default_backend() == "tpu"
    n_docs = int(os.environ.get("BENCH_KNN10M_DOCS",
                                10_000_000 if on_tpu else 100_000))
    dim = int(os.environ.get("BENCH_KNN10M_DIM",
                             256 if on_tpu else 64))
    n_q = 64
    rng = np.random.default_rng(31)
    t0 = time.time()
    # embedding-shaped corpus: vectors concentrate around semantic
    # centers (what gives IVF coarse quantization its bite); built in
    # chunks so the 10M x 256 slab streams instead of peaking 2x
    n_centers = 1024
    centers = rng.standard_normal((n_centers, dim)).astype(np.float32)
    emb = np.empty((n_docs, dim), dtype=np.float32)
    for lo in range(0, n_docs, 1 << 20):
        hi = min(lo + (1 << 20), n_docs)
        emb[lo:hi] = centers[rng.integers(0, n_centers, hi - lo)] \
            + rng.standard_normal((hi - lo, dim)).astype(np.float32) * 0.2
    norms = np.linalg.norm(emb, axis=1).astype(np.float32)
    exists = np.ones(n_docs, bool)
    log(f"knn_10m: {n_docs} x {dim} corpus in {time.time()-t0:.1f}s")

    t0 = time.time()
    prior_min = os.environ.get("ES_TPU_ANN_MIN_DOCS")
    os.environ["ES_TPU_ANN_MIN_DOCS"] = "1"
    try:
        ai = build_ann(emb, exists, "cosine", seed=7)
    finally:
        if prior_min is None:
            os.environ.pop("ES_TPU_ANN_MIN_DOCS", None)
        else:
            os.environ["ES_TPU_ANN_MIN_DOCS"] = prior_min
    assert ai is not None
    build_s = time.time() - t0
    nprobe = default_nprobe(ai.n_clusters)
    log(f"knn_10m: C={ai.n_clusters} ccap={ai.cluster_cap} "
        f"nprobe={nprobe} built in {build_s:.1f}s")

    dev = dict(vectors=jnp.asarray(emb, dtype=jnp.bfloat16),
               norms=jnp.asarray(norms), exists=jnp.asarray(exists),
               live=jnp.asarray(np.ones(n_docs, bool)),
               members=jnp.asarray(ai.members),
               centroids=jnp.asarray(ai.centroids),
               radii=jnp.asarray(ai.radii))
    # queries near members (the embedding-retrieval shape)
    queries = emb[rng.integers(0, n_docs, n_q)] \
        + rng.standard_normal((n_q, dim)).astype(np.float32) * 0.1
    qd = jnp.asarray(queries)

    def ivf(q):
        return ivf_topk(dev["vectors"], dev["norms"], dev["exists"],
                        dev["live"], dev["members"],
                        dev["centroids"], dev["radii"], q,
                        similarity="cosine", k=TOP_K, nprobe=nprobe)

    def exact(q):
        return knn_topk(dev["vectors"], dev["norms"], dev["exists"],
                        dev["live"], q, similarity="cosine", k=TOP_K)

    jax.block_until_ready(ivf(qd))          # compile
    jax.block_until_ready(exact(qd))
    ivf_s = best_time(lambda: jax.block_until_ready(ivf(qd)))
    exact_s = best_time(lambda: jax.block_until_ready(exact(qd)))
    ivf_qps = n_q / ivf_s
    exact_qps = n_q / exact_s

    s_a, i_a, stats = ivf(qd)
    s_e, _i_e = exact(qd)
    s_a, s_e = np.asarray(s_a), np.asarray(s_e)
    stats = np.asarray(stats)
    # SCORE-based recall@10 against the exact scan (ids are arbitrary
    # among bf16 score ties): a hit counts when it reaches the exact
    # k-th best
    hits = sum(int((s_a[r] >= s_e[r][-1] - 1e-6).sum())
               for r in range(n_q))
    recall = min(hits / (n_q * TOP_K), 1.0)
    if recall < 0.95:
        raise AssertionError(f"knn_10m recall@10 too low: {recall:.3f}")
    if int(stats[1]) <= 0:
        raise AssertionError("knn_10m: cluster-prune skip counter is "
                             "zero — the bound-vs-threshold prune "
                             "never fired")
    return {"metric": "knn_10m_qps", "value": round(ivf_qps, 1),
            "unit": "qps", "vs_baseline": round(ivf_qps / exact_qps, 2),
            "exact_qps": round(exact_qps, 1),
            "recall_at_10": round(recall, 3),
            "p50_ms": round(ivf_s / n_q * 1000, 3),
            "docs": n_docs, "dim": dim,
            "n_clusters": ai.n_clusters, "nprobe": nprobe,
            "build_s": round(build_s, 1),
            "clusters": {"probed": int(stats[0]),
                         "pruned": int(stats[1]),
                         "scored": int(stats[2])}}


def bench_hybrid_knn() -> dict:
    """Hybrid BM25+kNN msmarco leg: the knn bundle clause (one fused
    device dispatch per search) with the IDENTITY GATE — every fused
    response must be byte-identical to the unfused (sequential-math)
    oracle run of the same bodies."""
    from elasticsearch_tpu.search.shard_searcher import ShardReader
    from elasticsearch_tpu.search import executor as ex

    _fused_reset()
    n = max(N_DOCS // 4, 5_000)
    dim = 128
    rng = random.Random(17)
    nrng = np.random.default_rng(17)
    vocab = _vocab()
    weights = _zipf_weights(len(vocab))
    emb = nrng.standard_normal((n, dim)).astype(np.float32)
    t0 = time.time()
    docs = []
    for i in range(n):
        words = rng.choices(vocab, weights=weights,
                            k=rng.randint(20, 60))
        docs.append((str(i), {"passage": " ".join(words),
                              "emb": [float(x) for x in emb[i]]}))
    svc, seg, live = build_segment(docs, {"properties": {
        "passage": {"type": "text"},
        "emb": {"type": "dense_vector", "dims": dim,
                "similarity": "cosine"}}})
    reader = ShardReader("msmarco", [seg], {seg.seg_id: live}, svc)
    log(f"hybrid_knn: {n} passages x {dim}d in {time.time()-t0:.1f}s")

    rngq = random.Random(19)
    head = vocab[: max(len(vocab) // 8, 30)]
    wts = _zipf_weights(len(head))
    bodies = []
    for i in range(BATCH):
        terms = rngq.choices(head, weights=wts, k=2)
        qv = emb[rngq.randrange(n)] + nrng.standard_normal(
            dim).astype(np.float32) * 0.1
        bodies.append({"knn": {"field": "emb",
                               "query_vector": [float(x) for x in qv],
                               "k": TOP_K},
                       "query": {"match": {"passage": " ".join(terms)}},
                       "size": TOP_K})

    def run():
        t0 = time.time()
        out = reader.msearch([dict(b) for b in bodies])
        return time.time() - t0, out

    run()                                    # compile
    total_s, fused_out = run()
    qps = len(bodies) / total_s
    adm = ex.fused_scoring_stats()["admission"]
    if adm["admitted"] <= 0 or adm["knn"].get("query_rewrite", 0) <= 0:
        raise AssertionError(f"hybrid_knn: bundle never admitted {adm}")

    # identity gate vs the unfused sequential oracle
    os.environ["ES_TPU_FUSED"] = "0"
    try:
        oracle = reader.msearch([dict(b) for b in bodies])
    finally:
        os.environ.pop("ES_TPU_FUSED", None)
    for a, b in zip(fused_out, oracle):
        a, b = dict(a), dict(b)
        a["took"] = b["took"] = 0
        if json.dumps(a, sort_keys=True) != json.dumps(b, sort_keys=True):
            raise AssertionError("hybrid_knn: fused response diverged "
                                 "from the sequential oracle")
    return {"metric": "hybrid_bm25_knn_msmarco_qps",
            "value": round(qps, 1), "unit": "qps", "vs_baseline": 1.0,
            "identity": "fused == sequential oracle (byte)",
            "docs": n, "dim": dim, "batch": len(bodies),
            "admission": {"admitted": adm["admitted"],
                          "knn": adm["knn"],
                          "pallas_rejected": adm["pallas_rejected"]}}


# ---------------------------------------------------------------------------
# device-parallel index build (ROADMAP item 1): bulk ingest A/B,
# compaction under the write storm, ANN build wall-time
# ---------------------------------------------------------------------------

INGEST_DOCS = int(os.environ.get("BENCH_INGEST_DOCS", 20_000))


def _parse_corpus(docs, mapping):
    from elasticsearch_tpu.index.mapping import MapperService
    svc = MapperService(mapping=mapping)
    return [svc.parse(did, d) for did, d in docs]


def bench_bulk_ingest() -> dict:
    """Device vs host pack build A/B over the http_logs-shaped corpus,
    with the PACK-IDENTITY GATE: the device-built segment must carry
    the host builder's exact fingerprint (eager impacts, layouts,
    extrema bit-for-bit) — same-bytes-or-fallback is the device
    builder's whole contract (index/devbuild.py). On tunnel backends
    the A/B is additionally gated at >= 2x host docs/sec."""
    import jax
    from elasticsearch_tpu.index.segment import SegmentBuilder
    from elasticsearch_tpu.index import devbuild

    on_tpu = jax.default_backend() == "tpu"
    t0 = time.time()
    docs = make_corpus(INGEST_DOCS)
    mapping = {"properties": {"message": {"type": "text"},
                              "size": {"type": "long"},
                              "status": {"type": "keyword"}}}
    parsed = _parse_corpus(docs, mapping)
    log(f"bulk_ingest: {INGEST_DOCS} docs parsed in {time.time()-t0:.1f}s")

    builder = SegmentBuilder()
    for pd in parsed:
        builder.add(pd)

    # build() reads accumulated state without consuming it, so one
    # builder serves every A/B rep; the host pass stays pure-host
    # (no device pack dispatch) by never entering enable_scope
    host_s = best_time(lambda: builder.build("ab"))
    seg_host = builder.build("ab")

    devbuild.build_segment(builder, "ab")        # compile warm-up
    devbuild.reset_stats()
    dev_s = best_time(lambda: devbuild.build_segment(builder, "ab"))
    seg_dev = devbuild.build_segment(builder, "ab")
    if devbuild.stats()["builds_fallback"]:
        raise AssertionError("bulk_ingest: device build fell back to "
                             f"host: {devbuild.stats()}")
    if seg_dev.fingerprint() != seg_host.fingerprint():
        raise AssertionError(
            "bulk_ingest: device pack diverged from host pack "
            f"({seg_dev.fingerprint()} != {seg_host.fingerprint()})")

    dev_dps = INGEST_DOCS / dev_s
    host_dps = INGEST_DOCS / host_s
    speedup = dev_dps / host_dps
    if on_tpu and speedup < 2.0:
        raise AssertionError("bulk_ingest: device build "
                             f"{speedup:.2f}x host — gate is 2x on "
                             "tunnel backends")
    return {"metric": "bulk_ingest_docs_per_s", "value": round(dev_dps, 1),
            "unit": "docs/s", "vs_baseline": round(speedup, 2),
            "host_docs_per_s": round(host_dps, 1),
            "identity": "device pack == host pack (fingerprint)",
            "docs": INGEST_DOCS}


def bench_compaction_storm() -> dict:
    """Compaction wall-time under the PR 9 write storm shape: delta
    segments accumulate across refresh epochs, then one fold produces
    the new base. Device vs host A/B on the SAME delta stack, gated on
    the folded base's fingerprint matching across the two paths."""
    from elasticsearch_tpu.node import Node
    from elasticsearch_tpu.index import devbuild

    n_rounds = int(os.environ.get("BENCH_STORM_ROUNDS", 6))
    per_round = max(INGEST_DOCS // (n_rounds * 4), 256)
    mappings = {"properties": {"message": {"type": "text"},
                               "size": {"type": "long"},
                               "status": {"type": "keyword"}}}

    def storm(device: bool):
        node = Node({"index.number_of_shards": 1})
        node.create_index(
            "storm", settings={"index.streaming.delta": True,
                               "index.build.device": device,
                               # fold exactly once, under the timer
                               "index.delta.min_compact_docs": 1 << 30},
            mappings=mappings)
        docs = make_corpus(n_rounds * per_round, seed=91)
        for r in range(n_rounds):
            for did, d in docs[r * per_round: (r + 1) * per_round]:
                node.index_doc("storm", did, d)
            node.refresh("storm")
        eng = node.indices["storm"].shard(0)
        t0 = time.time()
        with devbuild.enable_scope(device):
            eng._compact_now()
        wall = time.time() - t0
        fps = sorted(s.fingerprint() for s in eng.segments)
        node.close()
        return wall, fps

    host_s, host_fps = storm(device=False)
    dev_s, dev_fps = storm(device=True)
    if dev_fps != host_fps:
        raise AssertionError("compaction_storm: device fold diverged "
                             "from host fold")
    return {"metric": "compaction_storm_wall_ms",
            "value": round(dev_s * 1000, 1), "unit": "ms",
            "vs_baseline": round(host_s / max(dev_s, 1e-9), 2),
            "host_wall_ms": round(host_s * 1000, 1),
            "identity": "device fold == host fold (fingerprint)",
            "docs": n_rounds * per_round, "deltas": n_rounds}


def bench_ann_build() -> dict:
    """IVF k-means build wall-time, device vs host Lloyd iterations.
    1M+ x 256 vectors on TPU; env-scaled proxy on the CPU CI backend
    (the device path compiles and runs everywhere — only the speedup
    claim needs the tunnel)."""
    import jax
    from elasticsearch_tpu.index.ann import build_ann
    from elasticsearch_tpu.index import devbuild

    on_tpu = jax.default_backend() == "tpu"
    n_docs = int(os.environ.get("BENCH_ANN_BUILD_DOCS",
                                1_000_000 if on_tpu else 50_000))
    dim = int(os.environ.get("BENCH_ANN_BUILD_DIM",
                             256 if on_tpu else 64))
    rng = np.random.default_rng(29)
    n_centers = 512
    centers = rng.standard_normal((n_centers, dim)).astype(np.float32)
    emb = np.empty((n_docs, dim), dtype=np.float32)
    for lo in range(0, n_docs, 1 << 20):
        hi = min(lo + (1 << 20), n_docs)
        emb[lo:hi] = centers[rng.integers(0, n_centers, hi - lo)] \
            + rng.standard_normal((hi - lo, dim)).astype(np.float32) * 0.2
    exists = np.ones(n_docs, bool)

    prior_min = os.environ.get("ES_TPU_ANN_MIN_DOCS")
    os.environ["ES_TPU_ANN_MIN_DOCS"] = "1"
    try:
        def run(device: bool):
            with devbuild.enable_scope(device):
                t0 = time.time()
                ai = build_ann(emb, exists, "cosine", seed=7)
                return time.time() - t0, ai
        run(device=True)                         # compile warm-up
        dev_s, ai_dev = run(device=True)
        host_s, ai_host = run(device=False)
    finally:
        if prior_min is None:
            os.environ.pop("ES_TPU_ANN_MIN_DOCS", None)
        else:
            os.environ["ES_TPU_ANN_MIN_DOCS"] = prior_min
    assert ai_dev is not None and ai_host is not None
    if ai_dev.n_clusters != ai_host.n_clusters:
        raise AssertionError("ann_build: cluster counts diverged")
    return {"metric": "ann_build_wall_s", "value": round(dev_s, 2),
            "unit": "s", "vs_baseline": round(host_s / max(dev_s, 1e-9), 2),
            "host_wall_s": round(host_s, 2),
            "docs": n_docs, "dim": dim,
            "n_clusters": ai_dev.n_clusters}


def bench_host_replace_recovery() -> dict:
    """Live-join recovery wall time (ISSUE 19): a 3-host scoped-session
    replica pod loses a member to a hard kill, the survivors quorum-
    evict it, and the metric is the wall time for a REPLACEMENT to join
    the live pod — hello/identity handshake, quorum admit, epoch
    rebuild — until every member (joiner included) serves again.
    Identity-gated: responses must be byte-identical across the whole
    kill -> evict -> replace arc on every driver (the replica-layout
    contract). CPU-runnable: scoped sessions are per-host device
    runtimes, so one process can play all three hosts over a LocalHub.
    The full-SPMD variant (global jax.distributed mesh, DCN admit) is
    hardware-gated — it needs a real multi-process pod (see
    tests/test_membership_procs.py for the real-OS-process arc)."""
    import jax
    from elasticsearch_tpu.cluster.transport import LocalHub
    from elasticsearch_tpu.index.mapping import MapperService
    from elasticsearch_tpu.index.segment import SegmentBuilder
    from elasticsearch_tpu.parallel.multihost import MultiHostIndex
    from elasticsearch_tpu.search.dispatch import membership_stats
    from elasticsearch_tpu.utils import faults
    from elasticsearch_tpu.utils.settings import Settings

    hosts = ["h0", "h1", "h2"]
    n_docs = 2000
    svc = MapperService(mapping={"properties": {
        "status": {"type": "keyword"},
        "size": {"type": "long"}}})

    def segs():
        b = SegmentBuilder()
        for i in range(n_docs):
            b.add(svc.parse(str(i), {
                "status": ["200", "404", "500"][i % 3], "size": i}))
        return [b.build("s0")]

    settings = Settings({
        "mesh.ping_interval": "-1", "mesh.ping_timeout": "500ms",
        "mesh.ping_retries": 3, "mesh.exec_backoff": "10ms"})
    hub = LocalHub()
    tr = {h: hub.create_transport(h, n_threads=6) for h in hosts}
    pod: dict[str, MultiHostIndex] = {}

    def mk(me, join=False):
        pod[me] = MultiHostIndex(
            tr[me], me, hosts, segs(), svc, {h: 1 for h in hosts},
            settings=settings, layout="replica", session="scoped",
            membership="quorum", join=join)

    threads = [threading.Thread(target=mk, args=(h,))
               for h in hosts[1:]]
    [t.start() for t in threads]
    mk(hosts[0])
    [t.join(timeout=120) for t in threads]
    body = {"query": {"term": {"status": "500"}}, "size": 10}
    try:
        a, b = pod["h0"], pod["h1"]
        base = _strip_timing(a.search(body))
        before = membership_stats.replacements.count

        # hard-kill h2; survivors evict it on heartbeats
        faults.configure("host_dead:host=h2")
        for _ in range(4):
            a.heartbeat_now()
        if not a.await_settled(60) or a.members != ("h0", "h1"):
            raise AssertionError(
                f"host_replace: eviction did not settle "
                f"({a.members}; {a.decisions})")
        if _strip_timing(a.search(body)) != base:
            raise AssertionError(
                "host_replace: survivor bytes drifted after eviction")

        # replacement joins the LIVE pod — this is the measured arc
        faults.clear()
        pod["h2"].close()
        tr["h2"].close()
        tr["h2"] = hub.create_transport("h2", n_threads=6)
        t0 = time.time()
        mk("h2", join=True)
        if not (a.await_settled(60) and b.await_settled(60)):
            raise AssertionError("host_replace: join did not settle")
        for h in hosts:
            if pod[h].members != ("h0", "h1", "h2"):
                raise AssertionError(
                    f"host_replace: [{h}] members {pod[h].members}")
            if _strip_timing(pod[h].search(body)) != base:
                raise AssertionError(
                    f"host_replace: [{h}] bytes drifted after join")
        recovery_ms = (time.time() - t0) * 1000.0
        if membership_stats.replacements.count != before + 1:
            raise AssertionError("host_replace: replacement not "
                                 "counted as a replacement")
        return {"metric": "host_replace_recovery_ms",
                "value": round(recovery_ms, 1), "unit": "ms",
                "vs_baseline": 1.0,
                "note": "replacement process joins a live scoped-"
                        "session pod (zero survivor restarts): "
                        "hello/identity handshake + quorum admit + "
                        "epoch rebuild until all 3 members serve "
                        "byte-identically; full-SPMD global-mesh "
                        f"variant hardware-gated (backend="
                        f"{jax.default_backend()})"}
    finally:
        faults.clear()
        for idx in pod.values():
            idx.close()
        for t in tr.values():
            t.close()


def main():
    import jax
    log(f"devices={jax.devices()} backend={jax.default_backend()}")
    results = [bench_http_logs(), bench_bool_msmarco(),
               bench_phrase_heavy()]
    tunnel_ms = measure_tunnel_ms()
    log(f"tunnel dispatch overhead p50: {tunnel_ms:.1f} ms")
    unbatched = bench_unbatched_traffic(tunnel_ms)
    svc, seg, live, zones, ts, fare = build_taxis()
    reader = _reader(svc, seg, live)
    results.append({"metric": "tunnel_dispatch_overhead_ms",
                    "value": round(tunnel_ms, 2), "unit": "ms",
                    "vs_baseline": 1.0,
                    "note": "flat per-dispatch round trip of the axon "
                            "dev tunnel (serving stack, not compute); "
                            "subtracted in single_device_p50_ms"})
    results.append(unbatched)
    results.append(bench_overload_mixed_tenant(tunnel_ms))
    results.append(bench_lone_query(tunnel_ms))
    results.append(bench_concurrent_index_search(tunnel_ms))
    results.append(bench_crash_recovery())
    results.append(bench_oversubscribed_corpus(tunnel_ms))
    results.append(bench_degraded_search(tunnel_ms))
    results.append(bench_terms_agg(reader, zones, ts, tunnel_ms))
    results.append(bench_date_histogram(reader, ts, fare, tunnel_ms))
    results.append(bench_knn())
    results.append(bench_knn_10m())
    results.append(bench_hybrid_knn())
    results.append(bench_bulk_ingest())
    results.append(bench_compaction_storm())
    results.append(bench_ann_build())
    results.append(bench_host_replace_recovery())
    for r in results:
        print(json.dumps(r))


if __name__ == "__main__":
    main()
