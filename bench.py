"""Benchmark: http_logs-style match-query BM25 QPS, TPU vs CPU baseline.

Mirrors BASELINE.json configs[0] ("match query BM25, Rally http_logs
track, single shard"): a single-shard full-text corpus of Apache-log-like
messages, batched match queries, top-10 hits.

The CPU baseline is an eager-scoring CSR scorer in numpy — the BM25S
formulation (PAPERS.md), which is the same algorithmic family the TPU
path uses, so the ratio isolates the hardware/XLA win rather than an
algorithm gap. (The reference's Lucene BulkScorer is typically SLOWER
than BM25S-style eager scoring at this corpus scale, so this baseline is
conservative.)

Prints ONE JSON line:
  {"metric": "http_logs_bm25_qps", "value": <tpu_qps>, "unit": "qps",
   "vs_baseline": <tpu_qps / cpu_qps>}
"""

from __future__ import annotations

import json
import os
import random
import sys
import time

import numpy as np

N_DOCS = int(os.environ.get("BENCH_DOCS", 100_000))
BATCH = int(os.environ.get("BENCH_BATCH", 1024))
N_BATCHES = int(os.environ.get("BENCH_BATCHES", 8))
TOP_K = 10

COMMON_WORDS = ["images", "french", "english", "venues", "tickets", "news",
                "sport", "history", "results", "teams", "athletes", "medal",
                "schedule", "village", "torch", "ceremony", "host", "city",
                "official", "site", "main", "index", "home", "photos",
                "stories", "accueil", "francais", "anglais", "cgi", "bin"]
METHODS = ["get", "post", "head"]
EXTS = ["html", "gif", "jpg", "cgi", "htm"]
VOCAB_SIZE = int(os.environ.get("BENCH_VOCAB", 4000))


def _vocab(rng: random.Random) -> list[str]:
    """Vocabulary: a head of common words plus a long tail of path
    tokens, like real web-log URLs."""
    return COMMON_WORDS + [f"p{i:05d}" for i in range(VOCAB_SIZE)]


def _zipf_weights(n: int) -> list[float]:
    w = [1.0 / (i + 3) ** 0.9 for i in range(n)]
    total = sum(w)
    return [x / total for x in w]


def make_corpus(n: int, seed: int = 42):
    rng = random.Random(seed)
    vocab = _vocab(rng)
    weights = _zipf_weights(len(vocab))

    def pick():
        return rng.choices(vocab, weights=weights)[0]

    zipf_paths = [[pick() for _ in range(rng.randint(2, 5))]
                  + [rng.choice(EXTS)] for _ in range(max(n // 25, 400))]
    docs = []
    for i in range(n):
        p = zipf_paths[min(int(rng.paretovariate(1.2)) - 1, len(zipf_paths) - 1)]
        msg = " ".join([rng.choice(METHODS)] + p
                       + [str(rng.choice([200, 200, 200, 404, 304]))])
        docs.append((str(i), {"message": msg,
                              "size": rng.randint(100, 100_000),
                              "status": str(rng.choice([200, 200, 200, 404, 500]))}))
    return docs


def make_queries(n: int, seed: int = 7):
    rng = random.Random(seed)
    vocab = _vocab(rng)
    head = vocab[: max(len(vocab) // 8, 30)]
    weights = _zipf_weights(len(head))
    out = []
    for _ in range(n):
        # query terms drawn from the head (what users actually search)
        words = rng.choices(head, weights=weights, k=rng.randint(1, 3))
        out.append(" ".join(words))
    return out


# ---------------------------------------------------------------------------
# CPU baseline: CSR eager-impact scorer (BM25S-style)
# ---------------------------------------------------------------------------


class CpuBM25:
    def __init__(self, seg):
        pf = seg.text["message"]
        self.term_index = pf.term_index
        self.indptr = pf.indptr
        self.doc_ids = pf.doc_ids
        # same precomputed impacts as the device path
        from elasticsearch_tpu.index.segment import BM25_K1, BM25_B, bm25_idf
        idf = bm25_idf(pf.df.astype(np.float64), pf.doc_count)
        k_d = BM25_K1 * (1 - BM25_B + BM25_B * pf.doc_len / pf.avg_len)
        imps = np.empty_like(pf.tfs, dtype=np.float32)
        for t in range(len(pf.terms)):
            s, e = int(pf.indptr[t]), int(pf.indptr[t + 1])
            tf = pf.tfs[s:e].astype(np.float64)
            imps[s:e] = idf[t] * tf * (BM25_K1 + 1.0) / (
                tf + k_d[pf.doc_ids[s:e]])
        self.imps = imps
        self.n = seg.capacity

    def search(self, qterms: list[str], k: int):
        scores = np.zeros(self.n, dtype=np.float32)
        for t in qterms:
            tid = self.term_index.get(t, -1)
            if tid < 0:
                continue
            s, e = int(self.indptr[tid]), int(self.indptr[tid + 1])
            if e - s < 2048:  # doc ids unique per term: fancy add is exact
                scores[self.doc_ids[s:e]] += self.imps[s:e]
            else:  # bincount wins for long postings
                scores += np.bincount(self.doc_ids[s:e],
                                      weights=self.imps[s:e],
                                      minlength=self.n).astype(np.float32)
        idx = np.argpartition(scores, -k)[-k:]
        order = idx[np.argsort(-scores[idx], kind="stable")]
        return order, scores[order]


def main():
    t_start = time.time()
    from elasticsearch_tpu.index.mapping import MapperService
    from elasticsearch_tpu.index.segment import SegmentBuilder
    from elasticsearch_tpu.search.query_dsl import QueryParser
    from elasticsearch_tpu.search.executor import (
        QueryBinder, execute_segment_async, collect_segment_result)
    import jax

    docs = make_corpus(N_DOCS)
    svc = MapperService(mapping={"properties": {
        "message": {"type": "text"},
        "size": {"type": "long"},
        "status": {"type": "keyword"}}})
    builder = SegmentBuilder()
    for did, d in docs:
        builder.add(svc.parse(did, d))
    seg = builder.build("bench")
    live = np.zeros(seg.capacity, dtype=bool)
    live[: seg.num_docs] = True
    print(f"# corpus: {N_DOCS} docs, {len(seg.text['message'].terms)} terms, "
          f"built in {time.time()-t_start:.1f}s; devices={jax.devices()}",
          file=sys.stderr)

    queries = make_queries(BATCH * (N_BATCHES + 2))
    parser = QueryParser(svc)
    binder = QueryBinder(seg, svc)

    def bind_batch(batch_queries):
        # bool-should form: every match query (1..3 terms) binds to the
        # same fused plan, so a whole batch is ONE device call
        return [binder.bind(parser.parse({"bool": {"should": [
            {"match": {"message": q}}], "minimum_should_match": 1}}))
                for q in batch_queries]

    # group queries by plan signature (match with 1/2/3 terms differ)
    def dispatch_batch(batch_queries):
        bounds = bind_batch(batch_queries)
        sig_groups = {}
        for b in bounds:
            sig_groups.setdefault(b.signature(), []).append(b)
        return [execute_segment_async(seg, live, group, TOP_K)
                for group in sig_groups.values()]

    def run_all(batches):
        """Pipelined serving: dispatch is async (the tunnel round trip
        overlaps compute of in-flight batches); collect everything."""
        pending = [dispatch_batch(b) for b in batches]
        results = [[collect_segment_result(out, lay, n)
                    for out, lay, n in outs] for outs in pending]
        return results

    batches = [queries[(i + 2) * BATCH: (i + 3) * BATCH]
               for i in range(N_BATCHES)]
    # warmup pass compiles every (plan, shape) bucket; the measured pass
    # is steady-state serving (what Rally measures after its warmup)
    t0 = time.time()
    run_all(batches)
    print(f"# warmup (incl. compiles): {time.time()-t0:.1f}s", file=sys.stderr)

    t0 = time.time()
    results = run_all(batches)
    tpu_s = time.time() - t0
    n_done = sum(len(b) for b in batches)
    tpu_qps = n_done / tpu_s

    # CPU baseline
    cpu = CpuBM25(seg)
    analyzer = svc.analysis.analyzer("standard")
    cpu_queries = queries[2 * BATCH: 2 * BATCH + min(n_done, 128)]
    t0 = time.time()
    for q in cpu_queries:
        cpu.search(analyzer.analyze(q), TOP_K)
    cpu_s = time.time() - t0
    cpu_qps = len(cpu_queries) / cpu_s

    # correctness gate: TPU top docs must agree with the CPU scorer on a
    # sample of the measured queries (matched recall, not just speed)
    sample = batches[0][:8]
    (ts, _tk, ti, tt, _tm), _ = [collect_segment_result(o, l, n)
                                 for o, l, n in dispatch_batch(sample)][0]
    for qi, q in enumerate(sample):
        cpu_ids, cpu_scores = cpu.search(analyzer.analyze(q), TOP_K)
        n_check = min(int(tt[qi]), TOP_K)
        # compare the score ladder (matched recall); duplicate log lines
        # produce score TIES whose ordering differs between the two
        # top-k implementations (TPU uses the Lucene doc-id rule)
        if not np.allclose(ts[qi][:n_check], cpu_scores[:n_check], rtol=1e-4):
            raise AssertionError(
                f"TPU/CPU score mismatch for query {q!r}: "
                f"{ts[qi][:n_check]} vs {cpu_scores[:n_check]}")
        # when the top score is clearly separated (not a tie plateau),
        # the winning doc must agree exactly
        if n_check >= 2 and cpu_scores[0] - cpu_scores[1] > 1e-3 * abs(
                cpu_scores[0]):
            if int(ti[qi][0]) != int(cpu_ids[0]):
                raise AssertionError(
                    f"TPU/CPU top-doc mismatch for query {q!r}")

    print(f"# tpu: {n_done} queries in {tpu_s:.2f}s = {tpu_qps:.0f} qps; "
          f"cpu baseline: {cpu_qps:.0f} qps", file=sys.stderr)
    print(json.dumps({
        "metric": "http_logs_bm25_qps",
        "value": round(tpu_qps, 1),
        "unit": "qps",
        "vs_baseline": round(tpu_qps / cpu_qps, 2),
    }))


if __name__ == "__main__":
    main()
